"""Minimal SSD-style detector: the reference's example/ssd pipeline on the
TPU-native stack — ImageDetIter feeding packed det labels, MultiBoxPrior
anchors, MultiBoxTarget matching with hard-negative mining, and
MultiBoxDetection decode+NMS at inference, all through the Gluon API with
the training step compiled to one XLA program.

Synthetic data (no network egress): random color blobs on noise, one box
per image. Runs on CPU in seconds; point ctx at mx.tpu() for the chip.

  python examples/ssd_detection.py --steps 100
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


class TinySSD(gluon.HybridBlock):
    """One-scale SSD head over a small conv trunk."""

    def __init__(self, num_classes=2, num_anchors=3, **kwargs):
        super().__init__(**kwargs)
        self._num_classes = num_classes
        self._num_anchors = num_anchors
        with self.name_scope():
            self.trunk = nn.HybridSequential()
            with self.trunk.name_scope():
                for filters in (16, 32, 64):
                    self.trunk.add(nn.Conv2D(filters, 3, strides=2,
                                             padding=1))
                    self.trunk.add(nn.BatchNorm())
                    self.trunk.add(nn.Activation("relu"))
            self.cls_head = nn.Conv2D(num_anchors * (num_classes + 1), 3,
                                      padding=1)
            self.box_head = nn.Conv2D(num_anchors * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        feat = self.trunk(x)
        cls = self.cls_head(feat)      # (B, A*(C+1), H, W)
        box = self.box_head(feat)      # (B, A*4, H, W)
        b = cls.shape[0]
        c1 = self._num_classes + 1
        # anchor index must be cell-major (hw*A + a) to line up with
        # MultiBoxPrior's layout and the box head's flattening
        cls = cls.reshape((b, self._num_anchors, c1, -1))
        cls = F.transpose(cls, axes=(0, 2, 3, 1)).reshape((b, c1, -1))
        box = F.transpose(box, axes=(0, 2, 3, 1)).reshape((b, -1))
        return feat, cls, box


def synth_batch(rng, batch, size=32):
    """Images with one bright square; labels [cls, x1, y1, x2, y2]."""
    imgs = rng.rand(batch, 3, size, size).astype(np.float32) * 0.2
    labels = np.full((batch, 1, 5), -1.0, np.float32)
    for i in range(batch):
        s = rng.randint(8, 16)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        cls = rng.randint(0, 2)
        imgs[i, cls, y0:y0 + s, x0:x0 + s] = 1.0
        labels[i, 0] = [cls, x0 / size, y0 / size,
                        (x0 + s) / size, (y0 + s) / size]
    return nd.array(imgs), nd.array(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = TinySSD()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()

    x, labels = synth_batch(rng, args.batch)
    feat, cls_pred, box_pred = net(x)
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.4, 0.25),
                                       ratios=(1.0, 2.0), clip=True)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    cls_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    box_loss = gluon.loss.HuberLoss()

    for step in range(args.steps):
        x, labels = synth_batch(rng, args.batch)
        with autograd.record():
            _, cls_pred, box_pred = net(x)
            bt, bm, ct = nd.contrib.MultiBoxTarget(
                anchors, labels, nd.softmax(cls_pred, axis=1),
                negative_mining_ratio=3.0, ignore_label=-1.0)
            keep = (ct >= 0).reshape((args.batch, -1, 1))
            lc = cls_loss(nd.transpose(cls_pred, axes=(0, 2, 1)), ct, keep)
            lb = box_loss(box_pred * bm, bt * bm)
            loss = lc.mean() + lb.mean()
        loss.backward()
        trainer.step(args.batch)
        if step % 10 == 0 or step == args.steps - 1:
            print("step %3d  loss %.4f (cls %.4f box %.4f)"
                  % (step, float(loss.asnumpy()),
                     float(lc.mean().asnumpy()),
                     float(lb.mean().asnumpy())))

    # inference: decode + NMS
    out = nd.contrib.MultiBoxDetection(
        nd.softmax(cls_pred, axis=1), box_pred, anchors,
        nms_threshold=0.45, threshold=0.2)
    dets = out.asnumpy()[0]
    kept = dets[dets[:, 0] >= 0]
    print("detections on image 0: %d rows (cls, score, box):" % len(kept))
    for row in kept[:5]:
        print("  cls=%d score=%.2f box=(%.2f, %.2f, %.2f, %.2f)"
              % (int(row[0]), row[1], *row[2:]))


if __name__ == "__main__":
    main()
