"""Word-level language model (reference: example/rnn/word_lm/train.py —
embedding -> stacked LSTM -> tied softmax, truncated BPTT, perplexity).

Uses a real tokenized corpus if --data points at a text file, else a
synthetic Zipf-distributed corpus (offline environment). Runs on mx.cpu()
or mx.tpu(); hybridized so the whole unrolled step compiles to one XLA
program.

  python examples/word_lm.py --ctx tpu --epochs 3
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn


class WordLM(gluon.HybridBlock):
    """Embedding -> LSTM stack -> (tied) vocab projection."""

    def __init__(self, vocab, emb=128, hidden=128, layers=2, dropout=0.2,
                 **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.embed = nn.Embedding(vocab, emb)
            self.lstm = rnn.LSTM(hidden, num_layers=layers,
                                 dropout=dropout, layout="NTC")
            self.drop = nn.Dropout(dropout)
            self.proj = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x, *args, **params):
        h = self.embed(x)                    # (N, T, E)
        h = self.lstm(h)                     # (N, T, H)
        h = self.drop(h)
        return self.proj(h)                  # (N, T, V)


def corpus(path, n_tokens=200_000, vocab=2000, seed=0):
    if path and os.path.exists(path):
        with open(path) as f:
            words = f.read().split()
        idx = {}
        data = np.array([idx.setdefault(w, len(idx)) for w in words],
                        np.int32)
        return data, len(idx)
    rng = np.random.RandomState(seed)
    # Zipf: realistic token frequency profile for the softmax
    data = (rng.zipf(1.3, n_tokens) % vocab).astype(np.int32)
    return data, vocab


def batchify(data, batch, seq):
    n = (len(data) - 1) // (batch * seq) * (batch * seq)
    x = data[:n].reshape(batch, -1)
    y = data[1:n + 1].reshape(batch, -1)
    for t in range(0, x.shape[1] - seq + 1, seq):
        yield x[:, t:t + seq], y[:, t:t + seq]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--data", default=None, help="tokenized text file")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=35)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--lr", type=float, default=1.0)
    p.add_argument("--clip", type=float, default=0.25)
    args = p.parse_args()

    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()
    data, vocab = corpus(args.data)
    print("corpus: %d tokens, vocab %d" % (len(data), vocab))

    with mx.Context(ctx):
        mx.random.seed(0)
        net = WordLM(vocab, emb=args.hidden, hidden=args.hidden,
                     layers=args.layers)
        net.initialize(mx.init.Xavier())
        net.hybridize(static_alloc=True)
        sce = gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": args.lr,
                                 "clip_gradient": args.clip})

        for epoch in range(args.epochs):
            total, count, t0 = 0.0, 0, time.time()
            for x_np, y_np in batchify(data, args.batch, args.seq):
                x = nd.array(x_np, ctx=ctx)
                y = nd.array(y_np, ctx=ctx)
                with autograd.record():
                    logits = net(x)
                    loss = sce(logits.reshape((-1, vocab)),
                               y.reshape((-1,))).mean()
                loss.backward()
                trainer.step(1)
                total += float(loss.asnumpy())
                count += 1
            ppl = math.exp(total / max(count, 1))
            tok_s = count * args.batch * args.seq / (time.time() - t0)
            print("epoch %d: ppl %.2f  (%.0f tok/s)" % (epoch, ppl, tok_s))
        # generation smoke: greedy continuation from a seed token
        seed_tok = nd.array(np.full((1, 1), 1, np.int32), ctx=ctx)
        out = []
        cur = seed_tok
        for _ in range(10):
            logits = net(cur)
            nxt = int(np.argmax(logits.asnumpy()[0, -1]))
            out.append(nxt)
            cur = nd.array(np.array([[nxt]], np.int32), ctx=ctx)
        print("greedy continuation token ids:", out)


if __name__ == "__main__":
    main()
