"""Convolutional autoencoder (reference: example/autoencoder — encoder/
decoder trained to reconstruct, the representation-learning classic).

Encoder: strided Conv2D stack to a small code; decoder: Conv2DTranspose
back to the input. Trains on the synthetic blob images used by the other
offline examples and asserts reconstruction error drops well below the
variance baseline.

  python examples/autoencoder.py --ctx tpu
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def build_ae(code_channels=8):
    enc = nn.HybridSequential(prefix="enc_")
    with enc.name_scope():
        enc.add(nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"))
        enc.add(nn.Conv2D(code_channels, 3, strides=2, padding=1,
                          activation="relu"))
    dec = nn.HybridSequential(prefix="dec_")
    with dec.name_scope():
        dec.add(nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                                   activation="relu"))
        dec.add(nn.Conv2DTranspose(1, 4, strides=2, padding=1))
    net = nn.HybridSequential()
    net.add(enc, dec)
    return net


def blobs(n, size=16, seed=0):
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    cx = rng.uniform(0.2, 0.8, (n, 1, 1, 1)).astype(np.float32)
    cy = rng.uniform(0.2, 0.8, (n, 1, 1, 1)).astype(np.float32)
    s = rng.uniform(0.05, 0.2, (n, 1, 1, 1)).astype(np.float32)
    img = np.exp(-((xx[None, None] - cx) ** 2 + (yy[None, None] - cy) ** 2)
                 / (2 * s ** 2))
    return img.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--steps", type=int, default=150)
    args = ap.parse_args()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    X = blobs(2048)
    var = float(((X - X.mean()) ** 2).mean())
    net = build_ae()
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.L2Loss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 2e-3})
    b = 64
    t0 = time.time()
    first = last = None
    for step in range(args.steps):
        lo = (step * b) % (len(X) - b)
        x = nd.array(X[lo:lo + b], ctx=ctx)
        with autograd.record():
            loss = loss_fn(net(x), x)
        loss.backward()
        tr.step(b)
        cur = float(loss.mean().asnumpy()) * 2
        first = first if first is not None else cur
        last = cur
    print("reconstruction MSE %.5f -> %.5f (pixel variance %.5f, %.0f "
          "steps, %.1fs)" % (first, last, var, args.steps,
                             time.time() - t0))
    assert last < 0.25 * var, (last, var)
    print("autoencoder OK: reconstruction beats the variance baseline 4x")


if __name__ == "__main__":
    main()
