"""Variable-length sequence modeling with BucketingModule.

reference: example/rnn/bucketing/ — sequences are grouped into length
buckets; one executor per bucket shares parameters (here: per-bucket jit
programs over shared arrays). The task is a synthetic copy-with-delay
language problem: predict token t-1 at position t. Demonstrates the
Module-API training loop (bind/init_params/init_optimizer/forward/
backward/update) across buckets.

  python examples/seq2seq_bucketing.py --epochs 5
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu.io.io import DataBatch, DataDesc

VOCAB = 32
EMBED = 16
HIDDEN = 32
BUCKETS = (8, 16, 24)


def sym_gen(seq_len):
    """Per-bucket symbol: embed -> unrolled tanh-RNN with SHARED weight
    variables (the classic bucketing construction: every bucket's graph
    reuses the same parameter symbols, so one parameter set serves all
    sequence lengths) -> per-step vocab logits."""
    data = mx.sym.Variable("data")            # (B, T) token ids
    label = mx.sym.Variable("softmax_label")  # (B, T)
    emb = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                           name="embed")
    wx = mx.sym.Variable("rnn_x_weight")
    bx = mx.sym.Variable("rnn_x_bias")
    wh = mx.sym.Variable("rnn_h_weight")
    wo = mx.sym.Variable("out_weight")
    bo = mx.sym.Variable("out_bias")
    h = None
    logits = []
    for t in range(seq_len):
        x_t = mx.sym.slice_axis(emb, axis=1, begin=t, end=t + 1)
        pre = mx.sym.FullyConnected(x_t, wx, bx, num_hidden=HIDDEN,
                                    name="fx%d" % t)
        if h is not None:
            pre = pre + mx.sym.FullyConnected(h, wh, num_hidden=HIDDEN,
                                              no_bias=True,
                                              name="fh%d" % t)
        h = mx.sym.tanh(pre, name="h%d" % t)
        logits.append(mx.sym.FullyConnected(h, wo, bo, num_hidden=VOCAB,
                                            name="fo%d" % t))
    stacked = mx.sym.stack(*logits, axis=1, name="stackT")   # (B,T,V)
    flat = mx.sym.reshape(stacked, shape=(-1, VOCAB), name="flat")
    lab = mx.sym.reshape(label, shape=(-1,), name="lab")
    out = mx.sym.SoftmaxOutput(flat, lab, name="softmax")
    return out, ("data",), ("softmax_label",)


def make_batches(rng, n, batch_size):
    """Copy-with-delay task bucketed by sequence length."""
    batches = []
    for _ in range(n):
        T = BUCKETS[rng.randint(len(BUCKETS))]
        toks = rng.randint(1, VOCAB, size=(batch_size, T))
        lab = np.concatenate([toks[:, :1] * 0, toks[:, :-1]], axis=1)
        batch = DataBatch(
            [mx.nd.array(toks.astype(np.float32))],
            [mx.nd.array(lab.astype(np.float32))],
            provide_data=[DataDesc("data", (batch_size, T))],
            provide_label=[DataDesc("softmax_label", (batch_size, T))])
        batch.bucket_key = T
        batches.append(batch)
    return batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-batches", type=int, default=24)
    p.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    args = p.parse_args()

    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()
    rng = np.random.RandomState(0)
    batches = make_batches(rng, args.num_batches, args.batch_size)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=max(BUCKETS),
                                 context=ctx)
    # bind shapes come from the bucket geometry, not from whichever
    # buckets the random batch draw happened to produce
    T = max(BUCKETS)
    mod.bind(
        data_shapes=[DataDesc("data", (args.batch_size, T))],
        label_shapes=[DataDesc("softmax_label", (args.batch_size, T))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})

    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        rng.shuffle(batches)
        metric.reset()
        for batch in batches:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            out = mod.get_outputs()[0]
            lab = batch.label[0].reshape((-1,))
            metric.update([lab], [out])
        print("epoch %2d  %s %.3f  (buckets used: %s)"
              % (epoch, *metric.get(),
                 sorted({b.bucket_key for b in batches})))


if __name__ == "__main__":
    main()
