"""Post-training INT8 quantization (reference: example/quantization/
imagenet_gen_qsym_mkldnn.py + python/mxnet/contrib/quantization.py —
train fp32, calibrate layer ranges on sample batches, convert to int8,
compare accuracy and output agreement).

Offline flow on a synthetic 10-class blob dataset: a small CNN is trained
fp32 to high accuracy, then quantized with each calibration mode
('naive' abs-max and 'entropy' KL thresholds). The script reports fp32 vs
int8 agreement and asserts the quantized net keeps accuracy — the same
acceptance shape the reference example documents (~<1% drop on ImageNet).

  python examples/quantize_cnn.py --ctx tpu
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def build_cnn(classes=10):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(16, 3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2))
        net.add(nn.Conv2D(32, 3, padding=1, activation="relu"))
        net.add(nn.MaxPool2D(2))
        net.add(nn.Flatten())
        net.add(nn.Dense(64, activation="relu"))
        net.add(nn.Dense(classes))
    return net


def blob_dataset(n, classes=10, size=16, seed=0):
    """Class-conditional blob images: class k = a gaussian bump at a fixed
    grid position with class-specific width."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, n)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    cx = (y % 5) * 0.2 + 0.1
    cy = (y // 5) * 0.5 + 0.25
    s = 0.08 + 0.04 * (y % 3)
    img = np.exp(-((xx[None] - cx[:, None, None]) ** 2 +
                   (yy[None] - cy[:, None, None]) ** 2) /
                 (2 * s[:, None, None] ** 2))
    img = img[:, None] + rng.normal(0, 0.15, (n, 1, size, size))
    return img.astype(np.float32), y.astype(np.int64)


def accuracy(net, X, Y, ctx, batch=128):
    correct = 0
    for lo in range(0, len(Y), batch):
        out = net(nd.array(X[lo:lo + batch], ctx=ctx))
        correct += int((out.asnumpy().argmax(-1) ==
                        Y[lo:lo + batch]).sum())
    return correct / len(Y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--train-steps", type=int, default=120)
    args = ap.parse_args()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    Xtr, Ytr = blob_dataset(4096, seed=0)
    Xte, Yte = blob_dataset(1024, seed=1)

    net = build_cnn()
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    b = 128
    t0 = time.time()
    for step in range(args.train_steps):
        lo = (step * b) % (len(Ytr) - b)
        x = nd.array(Xtr[lo:lo + b], ctx=ctx)
        y = nd.array(Ytr[lo:lo + b], ctx=ctx)
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(b)
    fp32_acc = accuracy(net, Xte, Yte, ctx)
    print("fp32: test acc %.3f (%.0f steps, %.1fs)"
          % (fp32_acc, args.train_steps, time.time() - t0))
    assert fp32_acc > 0.9, "fp32 baseline failed to train"

    calib = nd.array(Xtr[:256], ctx=ctx)
    fp32_out = net(nd.array(Xte[:256], ctx=ctx)).asnumpy()
    for mode in ("naive", "entropy"):
        qnet = qz.quantize_net(net, calib_data=calib, calib_mode=mode,
                               ctx=ctx)
        q_acc = accuracy(qnet, Xte, Yte, ctx)
        q_out = qnet(nd.array(Xte[:256], ctx=ctx)).asnumpy()
        agree = (q_out.argmax(-1) == fp32_out.argmax(-1)).mean()
        print("int8 (%s calibration): test acc %.3f, top-1 agreement "
              "with fp32 %.3f" % (mode, q_acc, agree))
        assert q_acc > fp32_acc - 0.02, (
            "int8 accuracy dropped too far: %.3f vs %.3f" % (q_acc, fp32_acc))
    print("quantization OK: int8 holds fp32 accuracy within 2%")


if __name__ == "__main__":
    main()
