"""Matrix-factorization recommender on sparse gradients (reference:
example/sparse/matrix_factorization/train.py — user/item Embeddings with
row_sparse gradients, dot-product score, MSE loss, SGD lazy update so
only the rows touched by a batch pay optimizer cost).

Synthetic MovieLens-like ratings offline: a low-rank ground-truth factor
model plus noise, so the MSE floor is known and the script asserts
training actually approaches it. Only the embedding rows referenced by
each batch receive gradient rows (grad_stype='row_sparse'), which is the
whole point of the reference example.

  python examples/matrix_factorization.py --ctx tpu --epochs 5
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


class MFNet(gluon.HybridBlock):
    """score(u, i) = <user_emb[u], item_emb[i]> + b_u + b_i."""

    def __init__(self, n_users, n_items, k=16, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.user = nn.Embedding(n_users, k, sparse_grad=True)
            self.item = nn.Embedding(n_items, k, sparse_grad=True)
            self.user_b = nn.Embedding(n_users, 1, sparse_grad=True)
            self.item_b = nn.Embedding(n_items, 1, sparse_grad=True)

    def hybrid_forward(self, F, user, item):
        p, q = self.user(user), self.item(item)
        score = F.sum(p * q, axis=-1)
        return score + self.user_b(user).reshape((-1,)) \
            + self.item_b(item).reshape((-1,))


def synthetic_ratings(n_users, n_items, n_obs, k=8, noise=0.1, seed=0):
    rng = np.random.RandomState(seed)
    U = rng.normal(0, 1.0 / np.sqrt(k), (n_users, k)).astype(np.float32)
    V = rng.normal(0, 1.0 / np.sqrt(k), (n_items, k)).astype(np.float32)
    users = rng.randint(0, n_users, n_obs).astype(np.int32)
    items = rng.randint(0, n_items, n_obs).astype(np.int32)
    ratings = (U[users] * V[items]).sum(-1) + \
        rng.normal(0, noise, n_obs).astype(np.float32)
    return users, items, ratings.astype(np.float32), noise ** 2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--users", type=int, default=2000)
    ap.add_argument("--items", type=int, default=1000)
    ap.add_argument("--obs", type=int, default=20000)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--factors", type=int, default=16)
    args = ap.parse_args()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    users, items, ratings, noise_floor = synthetic_ratings(
        args.users, args.items, args.obs)
    net = MFNet(args.users, args.items, k=args.factors)
    net.initialize(mx.init.Normal(0.1), ctx=ctx)

    loss_fn = gluon.loss.L2Loss()
    # momentum carries the bilinear problem off its flat start; with
    # lazy_update the momentum of rows absent from a batch is NOT decayed
    # (exactly the reference's rowsparse sgd_mom_update semantics)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 15.0, "momentum": 0.9,
                             "lazy_update": True})

    # sanity: the embedding grads really are row-sparse
    for name, p in net.collect_params().items():
        assert p.grad_stype == "row_sparse", (name, p.grad_stype)

    b = args.batch_size
    first_mse = None
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(args.obs)
        t0, se, n = time.time(), 0.0, 0
        for lo in range(0, args.obs - b + 1, b):
            idx = perm[lo:lo + b]
            u = nd.array(users[idx], ctx=ctx, dtype="int32")
            i = nd.array(items[idx], ctx=ctx, dtype="int32")
            r = nd.array(ratings[idx], ctx=ctx)
            with autograd.record():
                loss = loss_fn(net(u, i), r)
            loss.backward()
            # row_sparse grads: only the touched rows flow to the updater
            g = net.user.weight.grad()
            assert g.stype == "row_sparse"
            trainer.step(b)
            se += float(loss.mean().asnumpy()) * 2  # L2Loss halves
            n += 1
        mse = se / n
        if first_mse is None:
            first_mse = mse
        print("epoch %d: train MSE %.4f (noise floor %.4f, %.1fs)"
              % (epoch, mse, noise_floor, time.time() - t0))

    # full run must land near the noise floor; short runs just need a trend
    factor = 0.25 if args.epochs >= 8 else 0.95
    assert mse < first_mse * factor, (
        "MF failed to learn: first %.4f last %.4f" % (first_mse, mse))
    print("final MSE %.4f vs noise floor %.4f — learning OK" %
          (mse, noise_floor))


if __name__ == "__main__":
    main()
