"""DCGAN (reference: example/gluon/dc_gan/dcgan.py — generator of stacked
Conv2DTranspose+BN+ReLU, discriminator of strided Conv2D+BN+LeakyReLU,
alternating real/fake sigmoid-BCE updates with separate Trainers).

Runs on synthetic data by default (offline environment): the "dataset" is
a mixture of blurred blob images, enough to watch D/G losses reach the
usual adversarial equilibrium. Point --data at an .rec file of real
images to train on actual data. Both networks hybridize, so one
generator step and one discriminator step are each a single XLA program.

  python examples/dcgan.py --ctx tpu --epochs 3
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def build_generator(ngf=32, nc=3):
    """latent (B, Z, 1, 1) -> image (B, nc, 32, 32) in [-1, 1]."""
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # 1x1 -> 4x4
        net.add(nn.Conv2DTranspose(ngf * 4, 4, strides=1, padding=0,
                                   use_bias=False))
        net.add(nn.BatchNorm(), nn.Activation("relu"))
        # 4x4 -> 8x8
        net.add(nn.Conv2DTranspose(ngf * 2, 4, strides=2, padding=1,
                                   use_bias=False))
        net.add(nn.BatchNorm(), nn.Activation("relu"))
        # 8x8 -> 16x16
        net.add(nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                   use_bias=False))
        net.add(nn.BatchNorm(), nn.Activation("relu"))
        # 16x16 -> 32x32
        net.add(nn.Conv2DTranspose(nc, 4, strides=2, padding=1,
                                   use_bias=False))
        net.add(nn.Activation("tanh"))
    return net


def build_discriminator(ndf=32):
    """image (B, nc, 32, 32) -> logit (B, 1, 1, 1)."""
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 2, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.BatchNorm(), nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 4, 4, strides=2, padding=1, use_bias=False))
        net.add(nn.BatchNorm(), nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(1, 4, strides=1, padding=0, use_bias=False))
    return net


def synthetic_batches(batch, n_batches, nc=3, size=32, seed=0):
    """Blob-mixture images standing in for a real dataset offline."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size - 0.5
    for _ in range(n_batches):
        cx = rng.uniform(-0.3, 0.3, (batch, nc, 1, 1)).astype(np.float32)
        cy = rng.uniform(-0.3, 0.3, (batch, nc, 1, 1)).astype(np.float32)
        s = rng.uniform(0.05, 0.15, (batch, nc, 1, 1)).astype(np.float32)
        img = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * s ** 2)))
        yield (img * 2.0 - 1.0).astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batches", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--latent", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()
    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()

    gen, disc = build_generator(), build_discriminator()
    gen.initialize(mx.init.Normal(0.02), ctx=ctx)
    disc.initialize(mx.init.Normal(0.02), ctx=ctx)
    gen.hybridize(static_alloc=True)
    disc.hybridize(static_alloc=True)

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer_g = gluon.Trainer(gen.collect_params(), "adam",
                              {"learning_rate": args.lr, "beta1": 0.5})
    trainer_d = gluon.Trainer(disc.collect_params(), "adam",
                              {"learning_rate": args.lr, "beta1": 0.5})

    b = args.batch_size
    real_label = nd.ones((b,), ctx=ctx)
    fake_label = nd.zeros((b,), ctx=ctx)
    mx.random.seed(0)

    for epoch in range(args.epochs):
        t0, dl_sum, gl_sum, n = time.time(), 0.0, 0.0, 0
        for real_np in synthetic_batches(b, args.batches, seed=epoch):
            real = nd.array(real_np, ctx=ctx)
            latent = nd.random.normal(shape=(b, args.latent, 1, 1), ctx=ctx)

            # --- discriminator: maximize log D(x) + log(1 - D(G(z))) ---
            with autograd.record():
                out_real = disc(real).reshape((-1,))
                err_real = loss_fn(out_real, real_label)
                fake = gen(latent)
                out_fake = disc(fake.detach()).reshape((-1,))
                err_fake = loss_fn(out_fake, fake_label)
                err_d = err_real + err_fake
            err_d.backward()
            trainer_d.step(b)

            # --- generator: maximize log D(G(z)) ---
            with autograd.record():
                out = disc(fake).reshape((-1,))
                err_g = loss_fn(out, real_label)
            err_g.backward()
            trainer_g.step(b)

            dl_sum += float(err_d.mean().asnumpy())
            gl_sum += float(err_g.mean().asnumpy())
            n += 1
        print("epoch %d: loss_D %.4f loss_G %.4f (%.1fs)"
              % (epoch, dl_sum / n, gl_sum / n, time.time() - t0))

    # sample a grid from the trained generator (the reference saves PNGs;
    # offline we just report the dynamic range round-trips sanely)
    sample = gen(nd.random.normal(shape=(4, args.latent, 1, 1), ctx=ctx))
    lo, hi = float(sample.min().asnumpy()), float(sample.max().asnumpy())
    assert -1.001 <= lo <= hi <= 1.001, (lo, hi)
    print("generator sample range: [%.3f, %.3f] OK" % (lo, hi))


if __name__ == "__main__":
    main()
