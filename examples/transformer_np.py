"""Transformer encoder written entirely on mx.np / mx.npx.

reference: GluonNLP's BERT cells are written against mx.np arrays and
npx ops (npx.layer_norm, npx.interleaved_matmul_selfatt_*, npx.softmax,
npx.embedding); this example exercises the same surface end-to-end — a
small transformer encoder trained on a synthetic "sort the tokens" task
with autograd flowing through the np namespace.

  python examples/transformer_np.py --epochs 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np
npx = mx.npx

VOCAB, DIM, HEADS, SEQ = 16, 32, 4, 12


def init_params(rng):
    def W(*shape, s=0.08):
        return np.array((rng.randn(*shape) * s).astype("float32"))

    p = {
        "embed": W(VOCAB, DIM),
        "pos": W(SEQ, DIM),
        "qkv_w": W(3 * DIM, DIM), "qkv_b": np.zeros((3 * DIM,)),
        "proj_w": W(DIM, DIM), "proj_b": np.zeros((DIM,)),
        "ln1_g": np.ones((DIM,)), "ln1_b": np.zeros((DIM,)),
        "ffn1_w": W(4 * DIM, DIM), "ffn1_b": np.zeros((4 * DIM,)),
        "ffn2_w": W(DIM, 4 * DIM), "ffn2_b": np.zeros((DIM,)),
        "ln2_g": np.ones((DIM,)), "ln2_b": np.zeros((DIM,)),
        "out_w": W(VOCAB, DIM), "out_b": np.zeros((VOCAB,)),
    }
    for v in p.values():
        v.attach_grad()
    return p


def encoder(p, tokens):
    """tokens (B, S) int32 -> logits (B, S, VOCAB), all mx.np/npx calls."""
    B = tokens.shape[0]
    h = npx.embedding(tokens, p["embed"], input_dim=VOCAB,
                      output_dim=DIM) + p["pos"]
    # attention block rides the fused interleaved op surface: (S, B, 3C)
    x = np.transpose(h, (1, 0, 2))
    qkv = npx.fully_connected(x.reshape(-1, DIM), p["qkv_w"], p["qkv_b"],
                              num_hidden=3 * DIM, flatten=False)
    qkv = qkv.reshape(SEQ, B, 3 * DIM)
    att = npx.interleaved_matmul_selfatt_qk(qkv, heads=HEADS)
    att = npx.softmax(att, axis=-1)
    ctx = npx.interleaved_matmul_selfatt_valatt(qkv, att, heads=HEADS)
    ctx = npx.fully_connected(ctx.reshape(-1, DIM), p["proj_w"],
                              p["proj_b"], num_hidden=DIM, flatten=False)
    h = npx.layer_norm(x.reshape(-1, DIM) + ctx, p["ln1_g"], p["ln1_b"])
    # ffn
    f = npx.fully_connected(h, p["ffn1_w"], p["ffn1_b"],
                            num_hidden=4 * DIM, flatten=False)
    f = npx.activation(f, act_type="gelu")
    f = npx.fully_connected(f, p["ffn2_w"], p["ffn2_b"], num_hidden=DIM,
                            flatten=False)
    h = npx.layer_norm(h + f, p["ln2_g"], p["ln2_b"])
    logits = npx.fully_connected(h, p["out_w"], p["out_b"],
                                 num_hidden=VOCAB, flatten=False)
    return np.transpose(logits.reshape(SEQ, B, VOCAB), (1, 0, 2))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)
    p = init_params(rng)
    # hand-rolled adam on the np surface
    m = {k: np.zeros(v.shape) for k, v in p.items()}
    s2 = {k: np.zeros(v.shape) for k, v in p.items()}
    t = 0

    for epoch in range(args.epochs):
        tot, hits, count = 0.0, 0, 0
        for _ in range(args.steps):
            toks = rng.randint(0, VOCAB, (args.batch_size, SEQ))
            target = onp.sort(toks, axis=1)     # task: sort the tokens
            x = np.array(toks.astype("int32"), dtype="int32")
            y = np.array(target.astype("int32"), dtype="int32")
            with autograd.record():
                logits = encoder(p, x)
                logp = npx.log_softmax(logits, axis=-1)
                nll = -npx.pick(logp.reshape(-1, VOCAB),
                                y.reshape(-1).astype("float32"))
                loss = np.mean(nll)
            loss.backward()
            t += 1
            corr = float(onp.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t))
            for k, v in p.items():
                g = v.grad
                m[k] = 0.9 * m[k] + 0.1 * g
                s2[k] = 0.999 * s2[k] + 0.001 * np.square(g)
                v -= args.lr * corr * m[k] / (np.sqrt(s2[k]) + 1e-8)
                v.grad[:] = 0
            tot += float(loss.asnumpy())
            pred = np.argmax(logits, axis=-1).asnumpy()
            hits += int((pred == target).sum())
            count += target.size
        print("epoch %2d  loss %.4f  token-acc %.3f"
              % (epoch, tot / args.steps, hits / count))


if __name__ == "__main__":
    main()
