"""Factorization machine on libsvm data with rowsparse updates.

reference: example/sparse/factorization_machine/ — CSR batches through
LibSVMIter, autograd through the differentiable sparse dot, rowsparse
gradients pushed to a kvstore with a server-side optimizer (only the rows
each batch touched travel), lazy adagrad updates.

  python examples/sparse_fm.py --epochs 10 --dim 100
Uses a synthetic libsvm file unless --data points at a real one.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray import sparse as sp


def synth_libsvm(path, dim, n_samples, rng):
    w_true = rng.randn(dim).astype(np.float32)
    lines = []
    for _ in range(n_samples):
        nnz = rng.randint(3, max(4, dim // 10))
        idx = sorted(rng.choice(dim, size=nnz, replace=False))
        vals = rng.rand(nnz).astype(np.float32)
        y = 1 if sum(w_true[i] * v for i, v in zip(idx, vals)) > 0 else 0
        lines.append(str(y) + " " + " ".join(
            "%d:%.4f" % (i, v) for i, v in zip(idx, vals)))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="libsvm file")
    p.add_argument("--dim", type=int, default=100)
    p.add_argument("--factor-size", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--samples", type=int, default=2000)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    path = args.data
    if path is None:
        path = os.path.join(tempfile.mkdtemp(), "fm.libsvm")
        synth_libsvm(path, args.dim, args.samples, rng)
        print("synthetic libsvm:", path)

    dim, k, bs = args.dim, args.factor_size, args.batch_size
    w = nd.array(np.zeros((dim, 1), np.float32))
    v = nd.array((rng.randn(dim, k) * 0.05).astype(np.float32))
    b = nd.array(np.zeros((1,), np.float32))
    for t in (w, v, b):
        t.attach_grad()

    kv = mx.kv.create("local")
    kv.init(0, w)
    kv.init(1, v)
    kv.set_optimizer(mx.optimizer.create(
        "adagrad", learning_rate=args.lr, rescale_grad=1.0 / bs))

    def forward(csr, csr_sq):
        lin = sp.dot(csr, w)
        xv = sp.dot(csr, v)
        x2v2 = sp.dot(csr_sq, nd.square(v))
        pair = 0.5 * nd.sum(nd.square(xv) - x2v2, axis=1, keepdims=True)
        return lin + pair + b

    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=dim,
                          batch_size=bs)
    for epoch in range(args.epochs):
        it.reset()
        total, count, correct = 0.0, 0, 0
        for batch in it:
            csr = batch.data[0]
            sq = sp.CSRNDArray(csr._sp_data * csr._sp_data,
                               csr._sp_indices, csr._indptr, csr.shape)
            y = batch.label[0].reshape((-1, 1))
            with autograd.record():
                out = forward(csr, sq)
                loss = nd.mean(nd.log(1 + nd.exp(-(2 * y - 1) * out)))
            loss.backward()
            b -= args.lr * b.grad
            touched = np.unique(np.asarray(csr._sp_indices))
            rows = sp.jnp.asarray(touched.astype(np.int32))
            kv.push(0, sp.RowSparseNDArray(w.grad._read()[rows] * bs,
                                           rows, w.shape))
            kv.push(1, sp.RowSparseNDArray(v.grad._read()[rows] * bs,
                                           rows, v.shape))
            # pull only touched rows back into the local dense replicas
            # (reference: Parameter.row_sparse_data path)
            for key, param in ((0, w), (1, v)):
                tmp = sp.zeros("row_sparse", param.shape)
                kv.row_sparse_pull(key, out=tmp, row_ids=nd.array(touched))
                param._write(param._read().at[tmp._indices].set(
                    tmp._values))
            for t in (w, v, b):
                t.grad[:] = 0
            total += float(loss.asnumpy()) * y.shape[0]
            count += y.shape[0]
            correct += int(((out.asnumpy() > 0) ==
                            (y.asnumpy() > 0.5)).sum())
        print("epoch %2d  logloss %.4f  acc %.3f"
              % (epoch, total / count, correct / count))


if __name__ == "__main__":
    main()
