"""Factorization machine on libsvm data over vocab-sharded embedding tables.

reference: example/sparse/factorization_machine/ — CSR batches through
LibSVMIter, autograd through the differentiable sparse dot, rowsparse
gradients pushed to a kvstore (only the rows each batch touched travel).

Upgraded to the mx.embedding sharded path (ISSUE 17): the FM's linear and
factor tables live in `ShardedEmbedding` instances registered with the
kvstore via `kv.init_embedding`. Pushing a RowSparseNDArray gradient
dedups rows, runs the Pallas segment-sum scatter-add, and applies the
optimizer in place beside the owned rows; `kv.row_sparse_pull` reads the
touched rows back through the warmed `EmbeddingLookupService` — a
compiled fixed-bucket gather, zero retraces after the first epoch.

  python examples/sparse_fm.py --epochs 10 --dim 100
Uses a synthetic libsvm file unless --data points at a real one.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.embedding import ShardedEmbedding
from mxnet_tpu.ndarray import sparse as sp


def synth_libsvm(path, dim, n_samples, rng):
    w_true = rng.randn(dim).astype(np.float32)
    lines = []
    for _ in range(n_samples):
        nnz = rng.randint(3, max(4, dim // 10))
        idx = sorted(rng.choice(dim, size=nnz, replace=False))
        vals = rng.rand(nnz).astype(np.float32)
        y = 1 if sum(w_true[i] * v for i, v in zip(idx, vals)) > 0 else 0
        lines.append(str(y) + " " + " ".join(
            "%d:%.4f" % (i, v) for i, v in zip(idx, vals)))
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="libsvm file")
    p.add_argument("--dim", type=int, default=100)
    p.add_argument("--factor-size", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--optimizer", default="adam", choices=("sgd", "adam"))
    p.add_argument("--samples", type=int, default=2000)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    path = args.data
    if path is None:
        path = os.path.join(tempfile.mkdtemp(), "fm.libsvm")
        synth_libsvm(path, args.dim, args.samples, rng)
        print("synthetic libsvm:", path)

    dim, k, bs = args.dim, args.factor_size, args.batch_size

    # sharded master tables: the linear weights (dim, 1) and the FM
    # factors (dim, k). Optimizer state lives row-aligned beside the
    # owned rows (ZeRO pattern); local dense replicas below only mirror
    # the rows each batch touches.
    table_w = ShardedEmbedding(dim, 1, optimizer=args.optimizer,
                               learning_rate=args.lr, name="fm.linear")
    table_v = ShardedEmbedding(dim, k, optimizer=args.optimizer,
                               learning_rate=args.lr, seed=1,
                               name="fm.factors")

    kv = mx.kv.create("local")
    kv.init_embedding(0, table_w, max_batch=dim)
    kv.init_embedding(1, table_v, max_batch=dim)

    w = nd.array(np.asarray(table_w.gathered_weight()))
    v = nd.array(np.asarray(table_v.gathered_weight()))
    b = nd.array(np.zeros((1,), np.float32))
    for t in (w, v, b):
        t.attach_grad()

    def forward(csr, csr_sq):
        lin = sp.dot(csr, w)
        xv = sp.dot(csr, v)
        x2v2 = sp.dot(csr_sq, nd.square(v))
        pair = 0.5 * nd.sum(nd.square(xv) - x2v2, axis=1, keepdims=True)
        return lin + pair + b

    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=dim,
                          batch_size=bs)
    for epoch in range(args.epochs):
        it.reset()
        total, count, correct = 0.0, 0, 0
        for batch in it:
            csr = batch.data[0]
            sq = sp.CSRNDArray(csr._sp_data * csr._sp_data,
                               csr._sp_indices, csr._indptr, csr.shape)
            y = batch.label[0].reshape((-1, 1))
            with autograd.record():
                out = forward(csr, sq)
                loss = nd.mean(nd.log(1 + nd.exp(-(2 * y - 1) * out)))
            loss.backward()
            b -= args.lr * b.grad
            touched = np.unique(np.asarray(csr._sp_indices))
            rows = sp.jnp.asarray(touched.astype(np.int32))
            scale = 1.0 / bs
            kv.push(0, sp.RowSparseNDArray(w.grad._read()[rows] * scale,
                                           rows, w.shape))
            kv.push(1, sp.RowSparseNDArray(v.grad._read()[rows] * scale,
                                           rows, v.shape))
            # pull only touched rows back into the local dense replicas —
            # a warmed compiled gather (reference: Parameter.row_sparse_data)
            for key, param in ((0, w), (1, v)):
                tmp = sp.zeros("row_sparse", param.shape)
                kv.row_sparse_pull(key, out=tmp, row_ids=nd.array(touched))
                param._write(param._read().at[tmp._indices].set(
                    tmp._values))
            for t in (w, v, b):
                t.grad[:] = 0
            total += float(loss.asnumpy()) * y.shape[0]
            count += y.shape[0]
            correct += int(((out.asnumpy() > 0) ==
                            (y.asnumpy() > 0.5)).sum())
        print("epoch %2d  logloss %.4f  acc %.3f"
              % (epoch, total / count, correct / count))
    snap = mx.telemetry.snapshot()["counters"]
    print("sparse pushes %d  unique rows %d / %d  serve lookups %d"
          % (snap.get("embedding.push", 0),
             snap.get("embedding.push.unique_rows", 0),
             snap.get("embedding.push.rows", 0),
             snap.get("embedding.serve.lookup", 0)))


if __name__ == "__main__":
    main()
