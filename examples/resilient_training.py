"""Fault-tolerant training with mxnet_tpu.resilience.

Trains a small MLP under an adversarial fault plan — a flaky transport
endpoint at step 2, a simulated host preemption at step 5, and a
maintenance-event NOTICE observed by the preemption poller (which turns
into a proactive, zero-replay checkpoint) — and shows the run completing
anyway, with the recovery ledger and the telemetry counters that would
feed a fleet dashboard. Checkpoints run the coordinated two-phase commit
(`commit=True`; trivially elected on one process, fleet-elected on a pod).

Run:  JAX_PLATFORMS=cpu python examples/resilient_training.py
Try:  MXNET_TPU_FAULT_PLAN="train.step:hang:4:30" \
      MXNET_TPU_STEP_DEADLINE_S=2 python examples/resilient_training.py
      (a hung step becomes a StallError -> restore -> replay; its
      .format_report() post-mortem carries per-device buffer stats)
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, resilience, telemetry
from mxnet_tpu.gluon import nn

STEPS = 8
BATCH = 32


def build_net():
    mx.random.seed(7)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer


def main():
    rng = np.random.RandomState(0)
    X = rng.rand(STEPS, BATCH, 20).astype(np.float32)
    Y = rng.randint(0, 10, (STEPS, BATCH)).astype(np.float32)

    def batch_fn(i):  # deterministic per index: replayable after restore
        return nd.array(X[i]), nd.array(Y[i])

    net, trainer = build_net()
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer)

    # the same plan could come from MXNET_TPU_FAULT_PLAN in the environment;
    # the preempt.poll entry simulates a TPU-VM maintenance notice — the
    # listener converts it into a proactive (zero-replay) checkpoint
    plan = "run.step:error:2;run.step:preempt:5;preempt.poll:preempt:2"
    print("fault plan: %s" % plan)
    listener = resilience.PreemptionListener(poll_interval_s=0.05)
    with resilience.faults.inject(plan):
        runner = resilience.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=tempfile.mkdtemp(prefix="ckpt_"),
            ckpt_every=2, max_restarts=4, step_deadline_s=60,
            commit=True, preempt_listener=listener)
        report = runner.run(STEPS)
    listener.stop()

    print("\n%r" % report)
    print("losses: %s" % np.round(report.losses, 4).tolist())
    snap = telemetry.snapshot()["counters"]
    print("\nrecovery ledger (telemetry):")
    for name in sorted(snap):
        if name.startswith("resilience."):
            print("  %-40s %d" % (name, snap[name]))


if __name__ == "__main__":
    main()
