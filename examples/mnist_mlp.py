"""Config #1: MLP on MNIST (reference: example/mnist/ via Gluon).

Uses the real MNIST if present under --data-dir (idx format), else a
synthetic stand-in (offline environment). Runs on mx.cpu() or mx.tpu().

  python examples/mnist_mlp.py --ctx tpu --epochs 5 --hybridize
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def load_mnist(data_dir, n_synth=4096):
    try:
        from mxnet_tpu.gluon.data.vision import MNIST
        train = MNIST(root=data_dir, train=True)
        X = np.stack([np.asarray(im).reshape(-1) for im, _ in train]) / 255.0
        y = np.asarray([lab for _, lab in train], np.float32)
        return X.astype(np.float32), y
    except Exception:
        rng = np.random.RandomState(0)
        X = rng.rand(n_synth, 784).astype(np.float32)
        y = X[:, :10].argmax(axis=1).astype(np.float32)
        print("MNIST not found; using synthetic data (%d samples)" % n_synth)
        return X, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ctx", default="tpu", choices=["cpu", "tpu", "gpu"])
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--hybridize", action="store_true")
    p.add_argument("--data-dir", default=os.path.expanduser("~/.mxnet/datasets/mnist"))
    args = p.parse_args()
    ctx = getattr(mx, args.ctx)()

    X, y = load_mnist(args.data_dir)
    train_iter = mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(ctx=ctx)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    if args.hybridize:
        net.hybridize()
        loss_fn.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        train_iter.reset()
        metric.reset()
        tic = time.time()
        for batch in train_iter:
            data = batch.data[0].as_in_context(ctx)
            label = batch.label[0].as_in_context(ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
        name, acc = metric.get()
        print("Epoch[%d] Train-%s=%.4f Time cost=%.1f"
              % (epoch, name, acc, time.time() - tic))


if __name__ == "__main__":
    main()
