"""Config #5: Llama with TP+FSDP sharding over an ICI mesh (reference
north star; no reference analog — MXNet 1.x had only group2ctx manual MP).

Single chip runs the tiny config; on a pod, set --mesh to the real shape
(e.g. --mesh data=4,fsdp=4,model=4 on v5e-64) and pick --config llama3_8b.
Simulate multi-chip on CPU with:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/llama_sharded.py --mesh data=2,fsdp=2,model=2

Demonstrates the full native training path: fused sharded step (fwd+bwd+
collectives+adamw in ONE XLA program), checkpoint save + resume.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()
import jax
import jax.numpy as jnp

from mxnet_tpu.models.llama import CONFIGS, llama_init, llama_loss
from mxnet_tpu.parallel import (create_mesh, LLAMA_RULES, ShardedTrainStep,
                                save_train_state, restore_train_state,
                                latest_step)


def parse_mesh(spec):
    axes = {}
    for part in spec.split(","):
        k, v = part.split("=")
        axes[k.strip()] = int(v)
    return axes


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="llama_tiny", choices=list(CONFIGS))
    p.add_argument("--mesh", default="data=1",
                   help="e.g. data=4,fsdp=4,model=4")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--ckpt", default="")
    p.add_argument("--ckpt-every", type=int, default=50)
    args = p.parse_args()

    cfg = CONFIGS[args.config]
    mesh = create_mesh(**parse_mesh(args.mesh))
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)))

    params = llama_init(jax.random.PRNGKey(0), cfg)
    step = ShardedTrainStep(lambda p_, b: llama_loss(p_, b, cfg), params,
                            mesh, rules=LLAMA_RULES, optimizer="adamw",
                            lr=args.lr)
    params, opt_state = step.init()
    start = 0
    if args.ckpt and latest_step(args.ckpt) is not None:
        params, opt_state, start = restore_train_state(args.ckpt, mesh=mesh,
                                                       rules=LLAMA_RULES)
        print("resumed from step", start)

    key = jax.random.PRNGKey(1)
    for i in range(start, args.steps):
        key, sub = jax.random.split(key)
        toks = jax.random.randint(sub, (args.batch, args.seq + 1), 0,
                                  cfg.vocab_size)
        t0 = time.time()
        params, opt_state, loss = step(params, opt_state, {"tokens": toks})
        loss = float(loss)
        dt = time.time() - t0
        tput = args.batch * args.seq / dt
        print("step %d loss %.4f  %.0f tok/s" % (i, loss, tput))
        if args.ckpt and (i + 1) % args.ckpt_every == 0:
            save_train_state(args.ckpt, params, opt_state, i + 1)
    if args.ckpt:
        save_train_state(args.ckpt, params, opt_state, args.steps)


if __name__ == "__main__":
    main()
