"""mx.serve walkthrough: continuous batching, load shedding, chaos drills.

Runs on the CPU backend out of the box (tiny llama). Shows the full
robustness story: a burst of staggered requests served under continuous
batching (prompts prefilled in shared chunk windows), an oversized
request shed with a structured Overloaded, a MXNET_TPU_FAULT_PLAN kill
at serve.step recovered mid-stream with byte-identical output, and the
serving-v2 layers: shared-prefix KV reuse, speculative decoding, and
replayable sampling.

    JAX_PLATFORMS=cpu python examples/serving.py
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models.llama import CONFIGS, llama_init
from mxnet_tpu.resilience import faults

cfg = dataclasses.replace(CONFIGS["llama_tiny"], dtype=jnp.float32,
                          max_seq_len=64)
params = llama_init(jax.random.PRNGKey(0), cfg)

server = mx.serve.InferenceServer(params, cfg, kv_blocks=64, block_size=8,
                                  max_batch=8)
server.warmup()      # AOT-compile the chunk-prefill/decode/CoW programs

rng = np.random.RandomState(0)
requests = [mx.serve.Request(
    rng.randint(1, cfg.vocab_size - 1, size=rng.randint(4, 16)).tolist(),
    max_new_tokens=8 + i % 5) for i in range(10)]

print("== continuous batching ==")
handles = [server.submit(r) for r in requests]
try:      # admission control: too-big requests shed, they never OOM
    server.submit(mx.serve.Request([1] * 8, max_new_tokens=10_000))
except mx.serve.Overloaded as exc:
    print("shed:", exc.reason)
server.run()
for h in handles[:3]:
    print("%s -> %s tokens, ttft %.1f ms" % (h.id, len(h.result()),
                                             h.ttft_ms))
baseline = [h.result() for h in handles]

print("== kill serve.step mid-stream, byte-identical recovery ==")
server2 = mx.serve.InferenceServer(params, cfg, kv_blocks=64, block_size=8,
                                   max_batch=8).warmup()
with faults.inject("serve.step:error:3"):
    handles2 = [server2.submit(mx.serve.Request(
        r.prompt, max_new_tokens=r.max_new_tokens)) for r in requests]
    server2.run()
assert [h.result() for h in handles2] == baseline
snap = telemetry.snapshot()["counters"]
print("recovered: recoveries=%d requeued_streams=%d — outputs identical"
      % (snap["serve.recoveries"], snap["serve.requeued_streams"]))

print("== replica group: survive a replica death ==")
group = mx.serve.ReplicaGroup(params, cfg, replicas=2, kv_blocks=64,
                              block_size=8, max_batch=4, max_restarts=0)
group.warmup().start()
with faults.inject("serve.step:preempt:5"):
    handles3 = [group.submit(mx.serve.Request(
        r.prompt, max_new_tokens=r.max_new_tokens)) for r in requests]
    results = [h.result(timeout=60) for h in handles3]
group.stop()
assert results == baseline
print("alive replicas: %d/2 — all streams finished on the survivor"
      % group.alive_replicas)

print("== prefix sharing: N users of one system prompt ==")
server3 = mx.serve.InferenceServer(params, cfg, kv_blocks=64, block_size=8,
                                   max_batch=4).warmup()
system_prompt = rng.randint(1, cfg.vocab_size - 1, size=16).tolist()
h0 = server3.submit(mx.serve.Request(system_prompt + [7, 8],
                                     max_new_tokens=6))
server3.run()        # first user pays the prefix prefill; it is cached
h0.result()
shared = [server3.submit(mx.serve.Request(
    system_prompt + rng.randint(1, 255, size=3).tolist(),
    max_new_tokens=6)) for _ in range(3)]
server3.run()
snap = telemetry.snapshot()["counters"]
print("prefix hits=%d blocks_shared=%d cow=%d — later users skip the "
      "system prompt" % (snap.get("serve.prefix.hits", 0),
                         snap.get("serve.prefix.blocks_shared", 0),
                         snap.get("serve.prefix.cow", 0)))

print("== speculative decoding (draft rides the same programs) ==")
draft_cfg = dataclasses.replace(cfg, n_layers=1, dim=32, n_heads=2,
                                n_kv_heads=1, hidden_dim=64)
spec = mx.serve.InferenceServer(
    params, cfg, kv_blocks=64, block_size=8, max_batch=4,
    draft_params=llama_init(jax.random.PRNGKey(1), draft_cfg),
    draft_cfg=draft_cfg, spec_k=4).warmup()
handles4 = [spec.submit(mx.serve.Request(
    r.prompt, max_new_tokens=r.max_new_tokens)) for r in requests]
spec.run()
assert [h.result() for h in handles4] == baseline  # byte-identical
snap = telemetry.snapshot()["counters"]
print("spec: drafted=%d accepted=%d — output byte-identical to plain "
      "greedy" % (snap["serve.spec.drafted"], snap["serve.spec.accepted"]))

print("== sampling: replayable per-stream draws ==")
sampled = mx.serve.InferenceServer(params, cfg, kv_blocks=64,
                                   block_size=8, max_batch=2).warmup()
ha = sampled.submit(mx.serve.Request([5, 6, 7], max_new_tokens=8,
                                     temperature=0.8, top_p=0.95,
                                     seed=123))
sampled.run()
print("sampled tokens (seed=123):", ha.result())
