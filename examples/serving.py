"""mx.serve walkthrough: continuous batching, load shedding, chaos drills.

Runs on the CPU backend out of the box (tiny llama). Shows the full
robustness story: a burst of staggered requests served under continuous
batching, an oversized request shed with a structured Overloaded, and a
MXNET_TPU_FAULT_PLAN kill at serve.step recovered mid-stream with
byte-identical output.

    JAX_PLATFORMS=cpu python examples/serving.py
"""
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models.llama import CONFIGS, llama_init
from mxnet_tpu.resilience import faults

cfg = dataclasses.replace(CONFIGS["llama_tiny"], dtype=jnp.float32,
                          max_seq_len=64)
params = llama_init(jax.random.PRNGKey(0), cfg)

server = mx.serve.InferenceServer(params, cfg, kv_blocks=64, block_size=8,
                                  max_batch=8)
server.warmup()      # AOT-compile every prefill bucket + the decode program

rng = np.random.RandomState(0)
requests = [mx.serve.Request(
    rng.randint(1, cfg.vocab_size - 1, size=rng.randint(4, 16)).tolist(),
    max_new_tokens=8 + i % 5) for i in range(10)]

print("== continuous batching ==")
handles = [server.submit(r) for r in requests]
try:      # admission control: too-big requests shed, they never OOM
    server.submit(mx.serve.Request([1] * 8, max_new_tokens=10_000))
except mx.serve.Overloaded as exc:
    print("shed:", exc.reason)
server.run()
for h in handles[:3]:
    print("%s -> %s tokens, ttft %.1f ms" % (h.id, len(h.result()),
                                             h.ttft_ms))
baseline = [h.result() for h in handles]

print("== kill serve.step mid-stream, byte-identical recovery ==")
server2 = mx.serve.InferenceServer(params, cfg, kv_blocks=64, block_size=8,
                                   max_batch=8).warmup()
with faults.inject("serve.step:error:3"):
    handles2 = [server2.submit(mx.serve.Request(
        r.prompt, max_new_tokens=r.max_new_tokens)) for r in requests]
    server2.run()
assert [h.result() for h in handles2] == baseline
snap = telemetry.snapshot()["counters"]
print("recovered: recoveries=%d requeued_streams=%d — outputs identical"
      % (snap["serve.recoveries"], snap["serve.requeued_streams"]))

print("== replica group: survive a replica death ==")
group = mx.serve.ReplicaGroup(params, cfg, replicas=2, kv_blocks=64,
                              block_size=8, max_batch=4, max_restarts=0)
group.warmup().start()
with faults.inject("serve.step:preempt:5"):
    handles3 = [group.submit(mx.serve.Request(
        r.prompt, max_new_tokens=r.max_new_tokens)) for r in requests]
    results = [h.result(timeout=60) for h in handles3]
group.stop()
assert results == baseline
print("alive replicas: %d/2 — all streams finished on the survivor"
      % group.alive_replicas)
