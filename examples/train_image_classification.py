"""Image classification from a RecordIO pack — the reference's canonical
workflow (reference: example/image-classification/train_imagenet.py +
common/fit.py): pack images with tools/im2rec.py, stream them through
mx.io.ImageRecordIter, train a model_zoo network.

Two training paths, same data pipeline:
  --api module   symbolic Module.fit (reference default)
  --api gluon    Gluon + FusedTrainStep (the TPU-fast path)

With no --rec-train, a synthetic pack is generated (zero-egress
environment), which also demonstrates the pack-building API.

  python examples/train_image_classification.py --epochs 2
  python examples/train_image_classification.py --api module --epochs 2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd


def make_synth_pack(path, n=64, size=40, classes=10, seed=0):
    """Build a .rec/.idx pack of labeled synthetic images (stand-in for
    tools/im2rec.py over a real dataset)."""
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    rec, idx = path + ".rec", path + ".idx"
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        label = rng.randint(0, classes)
        # images with class-dependent mean so the task is learnable
        img = np.clip(rng.randn(size, size, 3) * 40 + 60 +
                      label * 12, 0, 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(label), i, 0), img, img_fmt=".jpg"))
    w.close()
    return rec, idx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec-train", default="", help=".rec pack (else synthetic)")
    ap.add_argument("--rec-train-idx", default="")
    ap.add_argument("--network", default="resnet18_v1")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-shape", default="3,32,32")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--api", choices=["gluon", "module"], default="gluon")
    ap.add_argument("--workdir", default="/tmp/mxtpu_imgcls")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)  # fit/Speedometer log at INFO

    data_shape = tuple(int(d) for d in args.image_shape.split(","))
    if args.rec_train:
        rec, idx = args.rec_train, args.rec_train_idx or None
    else:
        os.makedirs(args.workdir, exist_ok=True)
        rec, idx = make_synth_pack(os.path.join(args.workdir, "train"),
                                   classes=args.classes,
                                   size=data_shape[-1] + 8)

    train = mx.io.ImageRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=data_shape,
        batch_size=args.batch_size, shuffle=True, seed=1, rand_crop=True,
        rand_mirror=True, scale=1.0 / 255, preprocess_threads=4)

    ctx = mx.tpu() if mx.context.num_gpus() or os.environ.get(
        "MXNET_TEST_DEVICE") == "tpu" else mx.cpu()

    if args.api == "module":
        # symbolic path: zoo net traced to a symbol via SymbolBlock-style
        # export of the hybrid graph
        net = getattr(gluon.model_zoo.vision, args.network)(
            classes=args.classes)
        net.initialize(mx.init.Xavier(), ctx=mx.cpu())
        data = mx.sym.Variable("data")
        out = net(data)
        out = mx.sym.SoftmaxOutput(out, mx.sym.Variable("softmax_label"),
                                   name="softmax")
        mod = mx.mod.Module(out, context=mx.cpu(),
                            label_names=("softmax_label",))
        mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
                optimizer_params=(("learning_rate", args.lr),
                                  ("momentum", 0.9)),
                batch_end_callback=mx.callback.Speedometer(
                    args.batch_size, 10))
        return

    mx.random.seed(0)
    net = getattr(gluon.model_zoo.vision, args.network)(classes=args.classes)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize(static_alloc=True)
    first = next(iter(train))
    net(first.data[0].as_in_context(ctx))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    fused = gluon.FusedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 trainer)
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        t0, nbatch = time.time(), 0
        for batch in train:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            loss = fused(x, y)
            metric.update([y], [net(x)])
            nbatch += 1
        name, acc = metric.get()
        print("Epoch[%d] %s=%.4f loss=%.4f (%.1f img/s)"
              % (epoch, name, acc, float(loss.asnumpy()),
                 nbatch * args.batch_size / (time.time() - t0)))


if __name__ == "__main__":
    main()
