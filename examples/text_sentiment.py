"""Text sentiment classification: contrib.text Vocabulary + embeddings +
gluon.rnn BiLSTM, trained end-to-end — the reference ecosystem's
GluonNLP-style workflow (vocab -> embed -> encode -> classify) on the
TPU-native stack.

Synthetic corpus (no network egress): sequences of "positive" and
"negative" marker words among filler tokens; the label is which marker
family dominates. The model must learn word identity -> sentiment.

  python examples/text_sentiment.py --steps 60
"""
import argparse
import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.contrib import text
from mxnet_tpu.gluon import nn, rnn

POS = ["good", "great", "superb", "love", "happy"]
NEG = ["bad", "awful", "poor", "hate", "sad"]
FILLER = ["the", "a", "it", "was", "very", "movie", "film", "plot"]


def make_corpus(rng, n, seq_len=12):
    sents, labels = [], []
    for _ in range(n):
        label = rng.randint(0, 2)
        markers = POS if label else NEG
        k = rng.randint(2, 5)
        words = [markers[rng.randint(len(markers))] for _ in range(k)]
        words += [FILLER[rng.randint(len(FILLER))]
                  for _ in range(seq_len - k)]
        rng.shuffle(words)
        sents.append(words)
        labels.append(label)
    return sents, labels


def encode(vocab, sents, seq_len=12):
    pad = vocab.to_indices("<pad>")
    out = np.full((len(sents), seq_len), float(pad), np.float32)
    for i, words in enumerate(sents):
        idx = vocab.to_indices(words)[:seq_len]
        out[i, :len(idx)] = idx
    return out


class BiLSTMClassifier(gluon.HybridBlock):
    def __init__(self, vocab_size, embed_dim=32, hidden=32, classes=2,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, embed_dim)
            self.encoder = rnn.LSTM(hidden, bidirectional=True,
                                    layout="NTC")
            self.out = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        e = self.embed(x)                 # (N, T, E)
        h = self.encoder(e)               # (N, T, 2H)
        pooled = F.max(h, axis=1)         # max-over-time
        return self.out(pooled)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # vocabulary from the corpus (reference: contrib.text workflow)
    sents, labels = make_corpus(rng, 512)
    counter = collections.Counter(w for s in sents for w in s)
    vocab = text.Vocabulary(counter, reserved_tokens=["<pad>"])
    print("vocab size:", len(vocab))

    net = BiLSTMClassifier(len(vocab))
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    x_all = encode(vocab, sents)
    y_all = np.asarray(labels, np.float32)
    for step in range(args.steps):
        sel = rng.randint(0, len(sents), args.batch)
        x = nd.array(x_all[sel])
        y = nd.array(y_all[sel])
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(args.batch)
        if step % 20 == 0 or step == args.steps - 1:
            print("step %3d  loss %.4f" % (step, float(loss.asnumpy())))

    # eval on fresh data
    test_s, test_y = make_corpus(rng, 256)
    logits = net(nd.array(encode(vocab, test_s))).asnumpy()
    acc = (logits.argmax(1) == np.asarray(test_y)).mean()
    print("test accuracy: %.3f" % acc)
    assert acc > 0.9, "sentiment classifier failed to learn"


if __name__ == "__main__":
    main()
