"""Model parallelism: group2ctx, the TPU-native way.

reference: the MXNet 1.x model-parallel idiom is manual per-layer device
placement — `with mx.AttrScope(ctx_group='dev1'): ...` plus
`group2ctx={'dev1': gpu(0), 'dev2': gpu(1)}` at bind time
(example/model-parallel/, src/executor/graph_executor.cc). The TPU-native
equivalent is DECLARATIVE: name a mesh axis 'model' and give each layer's
parameters a PartitionSpec; GSPMD inserts the boundary collectives that
graph_executor's copy nodes did.

This example runs the same 2-layer Megatron-split MLP both ways:
  column-parallel fc1 (out dim sharded) -> row-parallel fc2 (in dim
  sharded, psum at the boundary) — and asserts the sharded loss equals
the replicated loss while training both.

Single chip degrades to replication (same program). Simulate a mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/model_parallel_mlp.py --model-parallel 4
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import (ShardingRules, ShardedTrainStep,
                                create_mesh)


def init_params(key, din, dh, dout):
    k1, k2 = jax.random.split(key)
    s1, s2 = (2.0 / din) ** 0.5, (2.0 / dh) ** 0.5
    return {
        "fc1": {"w": jax.random.normal(k1, (din, dh)) * s1,
                "b": jnp.zeros((dh,))},
        "fc2": {"w": jax.random.normal(k2, (dh, dout)) * s2,
                "b": jnp.zeros((dout,))},
    }


def loss_fn(params, batch):
    x, y = batch["x"], batch["y"]
    h = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    logits = h @ params["fc2"]["w"] + params["fc2"]["b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


# Megatron split, declared instead of placed:
#   fc1.w (din, dh): column-parallel — shard the OUTPUT dim over 'model'
#   fc2.w (dh, dout): row-parallel  — shard the INPUT dim; GSPMD inserts
#   the psum the reference's group2ctx copy-node placed by hand
MP_RULES = ShardingRules([
    (r"fc1/w", P(None, "model")),
    (r"fc1/b", P("model")),
    (r"fc2/w", P("model", None)),
], default=P())


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model-parallel", type=int, default=2)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--hidden", type=int, default=256)
    args = p.parse_args()

    n = len(jax.devices())
    mp = args.model_parallel if n >= args.model_parallel else 1
    print("%d device(s); model axis = %d" % (n, mp))

    din, dout = 32, 8
    rng = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rng.randn(args.batch, din).astype(np.float32)),
        "y": jnp.asarray(rng.randint(0, dout, args.batch)),
    }

    def train(mesh, rules, tag):
        params = init_params(jax.random.PRNGKey(0), din, args.hidden, dout)
        step = ShardedTrainStep(loss_fn, params, mesh, rules=rules,
                                optimizer="sgd", lr=0.1)
        p_, s_ = step.init()
        p_, s_, l0 = step(p_, s_, batch)
        t0 = time.time()
        for _ in range(args.steps):
            p_, s_, loss = step(p_, s_, batch)
        jax.block_until_ready(loss)
        dt = (time.time() - t0) / args.steps
        print("%s: loss %.4f -> %.4f  (%.2f ms/step)"
              % (tag, float(l0), float(loss), dt * 1e3))
        return float(l0), float(loss)

    mp_mesh = create_mesh(model=mp)
    l0_mp, lN_mp = train(mp_mesh, MP_RULES, "model-parallel")
    rep_mesh = create_mesh(data=1)
    l0_rep, lN_rep = train(rep_mesh, MP_RULES, "replicated  ")

    np.testing.assert_allclose(l0_mp, l0_rep, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(lN_mp, lN_rep, rtol=2e-3, atol=1e-4)
    print("sharded-vs-replicated parity OK — group2ctx semantics, "
          "zero manual copy nodes")


if __name__ == "__main__":
    main()
