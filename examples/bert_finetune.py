"""Config #3: BERT-base masked-LM training / fine-tune step.

reference: the fork served BERT through GluonNLP on the fused attention ops
(src/operator/contrib/transformer.cc); here the encoder is first-class
(models/bert.py) and the op surface is exposed as
mx.nd.contrib.interleaved_matmul_selfatt_qk/_valatt + npx.* for GluonNLP-
style code.

Runs a masked-LM training loop on synthetic data (no network egress; real
corpora drop in via mx.io.CSVIter / RecordIO) with the whole step — forward,
loss, backward, AdamW — compiled into one XLA program, then reports tok/s.

  python examples/bert_finetune.py --config bert_tiny --steps 20
  python examples/bert_finetune.py --config bert_base   # needs the TPU chip

Multi-chip (TP+FSDP over a mesh) via --mesh, same recipe as llama_sharded:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/bert_finetune.py --config bert_tiny --mesh data=2,fsdp=2,model=2
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.runtime import honor_jax_platforms_env
honor_jax_platforms_env()
import jax
import jax.numpy as jnp


def synth_batch(key, batch, seq, vocab):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "tokens": jax.random.randint(k1, (batch, seq), 0, vocab),
        "targets": jax.random.randint(k2, (batch, seq), 0, vocab),
        "mask": (jax.random.uniform(k3, (batch, seq)) < 0.15)
        .astype(jnp.int32),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="bert_tiny")
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--seq", type=int, default=0)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--mesh", default="",
                    help="e.g. data=2,fsdp=2,model=2 (default single device)")
    args = ap.parse_args()

    from mxnet_tpu.models.bert import CONFIGS, bert_init, bert_mlm_loss
    from mxnet_tpu.parallel.train_step import ShardedTrainStep
    from mxnet_tpu.parallel import create_mesh
    from mxnet_tpu.parallel.sharding import BERT_RULES

    cfg = CONFIGS[args.config]
    batch = args.batch or (64 if args.config != "bert_tiny" else 8)
    seq = args.seq or min(cfg.max_seq_len, 128)

    params = bert_init(jax.random.PRNGKey(0), cfg)
    if args.mesh:
        axes = dict(kv.split("=") for kv in args.mesh.split(","))
        mesh = create_mesh(**{k: int(v) for k, v in axes.items()})
    else:
        mesh = create_mesh(data=1, devices=jax.devices()[:1])
    step = ShardedTrainStep(lambda p, b: bert_mlm_loss(p, b, cfg), params,
                            mesh, rules=BERT_RULES, optimizer="adamw",
                            lr=args.lr)
    p, s = step.init()

    key = jax.random.PRNGKey(1)
    data = synth_batch(key, batch, seq, cfg.vocab_size)
    p, s, loss = step(p, s, data)          # compile
    jax.block_until_ready(loss)
    print("compiled; initial loss %.4f" % float(loss))

    t0 = time.perf_counter()
    losses = []
    for i in range(args.steps):
        key, sub = jax.random.split(key)
        data = synth_batch(sub, batch, seq, cfg.vocab_size)
        p, s, loss = step(p, s, data)
        losses.append(loss)
    jax.block_until_ready(losses[-1])
    dt = time.perf_counter() - t0
    tok_s = batch * seq * args.steps / dt
    print("config=%s batch=%d seq=%d: %.0f tok/s, loss %.4f -> %.4f"
          % (args.config, batch, seq, tok_s,
             float(losses[0]), float(losses[-1])))


if __name__ == "__main__":
    main()
