"""Parameter-shape inference hints.

TPU-native analog of the input-filling half of the reference's FInferShape
attributes (reference: src/operator/nn/fully_connected.cc (FCShape),
convolution.cc (ConvolutionShape), batch_norm.cc ...). Given known input
shapes (None = unknown) and the op's hyper-params, fill the parameter
shapes — used by symbolic infer_shape and Gluon deferred init.
"""
from __future__ import annotations

from . import registry as _reg


def _prod(xs):
    p = 1
    for x in xs:
        p *= x
    return p


def _hint(name):
    def deco(fn):
        _reg.get(name).shape_hint = fn
        return fn
    return deco


@_hint("FullyConnected")
def _fc_hint(shapes, kw):
    data = shapes[0]
    if data is None:
        return shapes
    num_hidden = kw.get("num_hidden")
    in_units = _prod(data[1:]) if kw.get("flatten", True) else data[-1]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (num_hidden, in_units)
    if len(out) > 2 and out[2] is None:
        out[2] = (num_hidden,)
    return out


@_hint("Convolution")
def _conv_hint(shapes, kw):
    data = shapes[0]
    if data is None:
        return shapes
    num_filter = kw.get("num_filter")
    num_group = kw.get("num_group", 1)
    kernel = tuple(kw.get("kernel"))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (num_filter, data[1] // num_group) + kernel
    if len(out) > 2 and out[2] is None:
        out[2] = (num_filter,)
    return out


@_hint("Deconvolution")
def _deconv_hint(shapes, kw):
    data = shapes[0]
    if data is None:
        return shapes
    num_filter = kw.get("num_filter")
    num_group = kw.get("num_group", 1)
    kernel = tuple(kw.get("kernel"))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[1], num_filter // num_group) + kernel
    if len(out) > 2 and out[2] is None:
        out[2] = (num_filter,)
    return out


def _channel_hint(axis_key="axis", default_axis=1):
    def hint(shapes, kw):
        data = shapes[0]
        if data is None:
            return shapes
        axis = kw.get(axis_key, default_axis)
        c = data[axis % len(data)]
        return [shapes[0]] + [(c,) if s is None else s for s in shapes[1:]]
    return hint


_reg.get("BatchNorm").shape_hint = _channel_hint("axis", 1)
_reg.get("LayerNorm").shape_hint = _channel_hint("axis", -1)
_reg.get("InstanceNorm").shape_hint = _channel_hint("axis", 1)
_reg.get("GroupNorm").shape_hint = _channel_hint("axis", 1)
_reg.get("RMSNorm").shape_hint = _channel_hint("axis", -1)


@_hint("Embedding")
def _embedding_hint(shapes, kw):
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (kw.get("input_dim"), kw.get("output_dim"))
    return out


@_hint("SoftmaxOutput")
def _softmax_output_hint(shapes, kw):
    data = shapes[0]
    if data is None:
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        if kw.get("multi_output"):
            out[1] = (data[0],) + tuple(data[2:])
        else:
            out[1] = tuple(data[:-1])
    return out


@_hint("LinearRegressionOutput")
def _linreg_hint(shapes, kw):
    data = shapes[0]
    if data is None:
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = data
    return out


_reg.get("MAERegressionOutput").shape_hint = _linreg_hint
_reg.get("LogisticRegressionOutput").shape_hint = _linreg_hint


@_hint("LeakyReLU")
def _leaky_hint(shapes, kw):
    data = shapes[0]
    if data is None or kw.get("act_type") != "prelu":
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[1],)
    return out


@_hint("RNN")
def _rnn_hint(shapes, kw):
    """Fill the packed parameter vector and begin-state shapes from the
    data shape (reference: rnn.cc RNNShape; layout per rnn_ops.py)."""
    data = shapes[0]
    if data is None:
        return shapes
    from .rnn_ops import rnn_param_size
    mode = kw.get("mode", "lstm")
    h = kw.get("state_size")
    L = kw.get("num_layers", 1)
    ndir = 2 if kw.get("bidirectional") else 1
    n = rnn_param_size(mode, data[2], h, L, kw.get("bidirectional", False))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (n,)
    for i in (2, 3):
        if len(out) > i and out[i] is None:
            out[i] = (L * ndir, data[1], h)
    return out
