"""Neural-net ops.

TPU-native analog of the reference's src/operator/nn/* (reference:
fully_connected.cc, convolution.cc, deconvolution.cc, pooling.cc,
batch_norm.cc, layer_norm.cc, activation.cc, leaky_relu.cc, dropout.cc,
softmax.cc) and src/operator/softmax_output.cc. Convs and matmuls lower to the
MXU via lax.conv_general_dilated / dot_general; there is no cuDNN-autotune
analog because XLA picks tilings (reference's CudnnConvolutionOp algo
selection collapses into the compiler).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias
from ..base import np_dtype


def _pair(v, n):
    if v is None:
        return (0,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v if len(v) == n else v * n


# ---------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------
@register("FullyConnected")
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True):
    """y = x W^T + b; weight is (num_hidden, in_units) like the reference."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = jnp.matmul(x, jnp.transpose(weight))
    if bias is not None and not no_bias:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# Convolution (reference: src/operator/nn/convolution.cc) — NCHW/OIHW layout
# to match the reference API; XLA relayouts internally for the MXU.
# ---------------------------------------------------------------------------
def layout_info(layout, nd, op="Convolution"):
    """Validate an MXNet layout string for nd spatial dims. Returns
    (layout, channels_last). The single source of truth for which layouts
    exist — gluon layers and ops all consult this."""
    spatial = "DHW"[3 - nd:]
    if layout is None:
        layout = "NC" + spatial
    if layout == "NC" + spatial:
        return layout, False
    if layout == "N" + spatial + "C":
        return layout, True
    raise ValueError("%s: unsupported layout %r for %dD (expected %r or %r)"
                     % (op, layout, nd, "NC" + spatial,
                        "N" + spatial + "C"))


def _conv_layouts(layout, nd):
    """layout -> (data_layout, weight_layout). Channels-first weights are
    OI+spatial; channels-last (reference: NHWC convs, GPU-only there) use
    O+spatial+I — weight (num_filter, *kernel, C/groups)."""
    layout, last = layout_info(layout, nd)
    spatial = "DHW"[3 - nd:]
    return layout, ("O" + spatial + "I") if last else ("OI" + spatial)


@register("Convolution")
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, no_bias=False,
                 layout=None, cudnn_tune=None, cudnn_off=None, workspace=None):
    nd = len(kernel) if kernel is not None else data.ndim - 2
    stride = _pair(stride if stride else 1, nd)
    dilate = _pair(dilate if dilate else 1, nd)
    pad = _pair(pad if pad else 0, nd)
    dlay, wlay = _conv_layouts(layout, nd)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (dlay, wlay, dlay))
    # no preferred_element_type: the MXU accumulates bf16 convs in fp32
    # natively, and a widened output dtype breaks the conv transpose rule
    # (fp32 cotangent x bf16 weight) under autograd
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        shape = [1] * out.ndim
        shape[dlay.index("C")] = -1
        out = out + bias.reshape(shape)
    return out


@register("Deconvolution")
def _deconvolution(data, weight, bias=None, kernel=None, stride=None,
                   dilate=None, pad=None, adj=None, num_filter=None,
                   num_group=1, no_bias=True, target_shape=None, layout=None,
                   cudnn_tune=None, cudnn_off=None, workspace=None):
    """reference: src/operator/nn/deconvolution.cc (transposed conv)."""
    nd = len(kernel)
    stride = _pair(stride if stride else 1, nd)
    pad = _pair(pad if pad else 0, nd)
    adj = _pair(adj if adj else 0, nd)
    spatial = "DHW"[3 - nd:]
    _, last = layout_info(layout, nd, "Deconvolution")
    if last:
        raise NotImplementedError(
            "Deconvolution: channels-last layouts not implemented")
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "IO" + spatial, "NC" + spatial))
    pads = []
    for i in range(nd):
        k = (kernel[i] - 1) * 1 + 1
        lo = k - 1 - pad[i]
        hi = k - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    out = lax.conv_general_dilated(
        data, weight, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------
@register("Pooling")
def _pooling(data, kernel=None, pool_type="max", global_pool=False,
             stride=None, pad=None, pooling_convention="valid",
             count_include_pad=True, cudnn_off=None, layout=None, p_value=2):
    nd = data.ndim - 2
    _, channels_last = layout_info(layout, nd, "Pooling")
    spatial_axes = (tuple(range(1, 1 + nd)) if channels_last
                    else tuple(range(2, data.ndim)))
    if global_pool:
        if pool_type == "max":
            return jnp.max(data, axis=spatial_axes, keepdims=True)
        return jnp.mean(data, axis=spatial_axes, keepdims=True)
    kernel = _pair(kernel, nd)
    stride = _pair(stride if stride else 1, nd)
    pad = _pair(pad if pad else 0, nd)
    if channels_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad high side enough that ceil division is covered
        sp_pads = []
        for i, ax in enumerate(spatial_axes):
            in_sz = data.shape[ax]
            out_sz = -(-(in_sz + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - in_sz - pad[i]
            sp_pads.append((pad[i], max(pad[i], needed)))
    else:
        sp_pads = [(p, p) for p in pad]
    if channels_last:
        pads = [(0, 0)] + sp_pads + [(0, 0)]
    else:
        pads = [(0, 0), (0, 0)] + sp_pads
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / counts
    if pool_type == "lp":
        s = lax.reduce_window(jnp.abs(data) ** p_value, 0.0, lax.add,
                              window, strides, pads)
        return s ** (1.0 / p_value)
    raise ValueError("unknown pool_type " + pool_type)


alias("Pooling", "pooling")


# ---------------------------------------------------------------------------
# Normalization (reference: batch_norm.cc, layer_norm.cc, instance_norm.cc,
# group_norm.cc, l2_normalization.cc)
# ---------------------------------------------------------------------------
@register("BatchNorm")
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=None):
    """Normalization math only; the moving-average update is done by the
    caller (Gluon layer / executor) functionally — reference mutates aux
    states inside the op (batch_norm.cc), which XLA forbids."""
    red = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    if use_global_stats:
        mean, var = moving_mean, moving_var
    else:
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red)
        var = jnp.var(x32, axis=red)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    # normalize in fp32, emit in the input dtype (reference cudnn BN does
    # fp32 internal math for fp16 inputs) — keeps a bf16 conv chain bf16
    # even when gamma/beta/stats are kept fp32 by BatchNorm.cast
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    out = ((data.astype(jnp.float32) -
            mean.reshape(shape).astype(jnp.float32)) * inv.reshape(shape) *
           g.reshape(shape).astype(jnp.float32) +
           beta.reshape(shape).astype(jnp.float32)).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm")
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """reference: src/operator/nn/layer_norm.cc."""
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    out = ((x32 - mean) * inv).astype(data.dtype)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = out * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("InstanceNorm")
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("GroupNorm")
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + rest)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def _l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        red = tuple(range(1, data.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    elif mode == "channel":
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
    else:  # spatial
        red = tuple(range(2, data.ndim))
        nrm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
    return data / nrm


@register("RMSNorm")
def _rms_norm(data, gamma, axis=-1, eps=1e-6):
    """TPU-era extension (used by Llama); not in the reference op set."""
    x32 = data.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
    out = (x32 * lax.rsqrt(ms + eps)).astype(data.dtype)
    return out * gamma


# ---------------------------------------------------------------------------
# Activations (reference: activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------
@register("Activation")
def _activation(data, act_type="relu"):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(data, approximate=True)
    if act_type == "silu" or act_type == "swish":
        return jax.nn.silu(data)
    raise ValueError("unknown act_type " + act_type)


@register("LeakyReLU")
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, key=None):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(data >= 0, data, a * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    raise ValueError("unknown act_type " + act_type)


@register("hard_sigmoid")
def _hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


# ---------------------------------------------------------------------------
# Softmax family (reference: softmax.cc, log_softmax, softmin, SoftmaxOutput)
# ---------------------------------------------------------------------------
@register("softmax")
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False,
             dtype=None):
    x = data
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        mask_shape = [1] * x.ndim
        mask_shape[axis] = x.shape[axis]
        mask = steps.reshape(mask_shape) < length.reshape(
            length.shape + (1,) * (x.ndim - length.ndim))
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x.astype(jnp.float32), axis=axis)
    return out.astype(np_dtype(dtype) if dtype else data.dtype)


@register("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data if not temperature or temperature == 1.0 else data / temperature
    out = jax.nn.log_softmax(x.astype(jnp.float32), axis=axis)
    return out.astype(np_dtype(dtype) if dtype else data.dtype)


@register("softmin")
def _softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("SoftmaxOutput")
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    """reference: src/operator/softmax_output.cc — forward is softmax; the
    fused CE gradient is produced by the custom VJP below."""
    return jax.nn.softmax(data, axis=1 if multi_output else -1)


# SoftmaxOutput's gradient is (softmax - onehot(label)) * grad_scale — the
# fused form the reference hand-codes. Express it as a custom VJP.
def _softmax_output_make():
    import functools
    from .registry import get

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
    def so(data, label, grad_scale, ignore_label, multi_output, use_ignore,
           normalization, smooth_alpha):
        return jax.nn.softmax(data, axis=1 if multi_output else -1)

    def fwd(data, label, grad_scale, ignore_label, multi_output, use_ignore,
            normalization, smooth_alpha):
        out = jax.nn.softmax(data, axis=1 if multi_output else -1)
        return out, (out, label)

    def bwd(grad_scale, ignore_label, multi_output, use_ignore, normalization,
            smooth_alpha, res, g):
        out, label = res
        axis = 1 if multi_output else -1
        depth = out.shape[axis]
        oh = jax.nn.one_hot(label.astype(jnp.int32), depth, axis=axis,
                            dtype=out.dtype)
        if smooth_alpha:
            oh = oh * (1 - smooth_alpha) + smooth_alpha / depth
        grad = (out - oh) * grad_scale
        keep = None
        if use_ignore:
            keep = (label != ignore_label).astype(out.dtype)
            keep = jnp.expand_dims(keep, axis=axis)
            grad = grad * keep
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid" and keep is not None:
            n = jnp.maximum(jnp.sum(keep), 1.0)
            grad = grad / n
        return grad, jnp.zeros_like(label)

    so.defvjp(fwd, bwd)
    op = get("SoftmaxOutput")
    op.fn = lambda data, label, grad_scale=1.0, ignore_label=-1.0, \
        multi_output=False, use_ignore=False, preserve_shape=False, \
        normalization="null", out_grad=False, smooth_alpha=0.0: so(
            data, label, grad_scale, ignore_label, multi_output, use_ignore,
            normalization, smooth_alpha)


_softmax_output_make()
alias("SoftmaxOutput", "Softmax")


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    lse = jax.scipy.special.logsumexp(data, axis=-1)
    picked = jnp.take_along_axis(
        data, label.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return jnp.sum(lse - picked)


@register("LinearRegressionOutput")
def _linear_regression_output(data, label, grad_scale=1.0):
    return data


@register("MAERegressionOutput")
def _mae_regression_output(data, label, grad_scale=1.0):
    return data


@register("LogisticRegressionOutput")
def _logistic_regression_output(data, label, grad_scale=1.0):
    return jax.nn.sigmoid(data)


# Like SoftmaxOutput, the regression heads are TRAINING-output ops: the
# backward is the hand-coded loss gradient (out - label), NOT the vjp of
# the forward (identity/sigmoid would pass the head cotangent through,
# making the "gradient" independent of the parameters — the silent-ones
# bug the SVRG tests caught). reference: src/operator/regression_output.cc
# (LinearRegressionBackward / MAERegressionBackward /
# LogisticRegressionBackward), each scaled by grad_scale / num_output.
def _regression_output_make(name, fwd_fn, residual_fn):
    import functools
    from .registry import get

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def ro(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, data, label)

    def bwd(grad_scale, res, g):
        out, data, label = res
        num_output = 1
        for s in data.shape[1:]:
            num_output *= s
        lab = label.reshape(data.shape).astype(out.dtype)
        grad = residual_fn(out, data, lab) * (grad_scale / num_output)
        return grad.astype(data.dtype), jnp.zeros(label.shape, label.dtype)

    ro.defvjp(fwd, bwd)
    op = get(name)
    op.fn = lambda data, label, grad_scale=1.0: ro(data, label, grad_scale)


_regression_output_make("LinearRegressionOutput", lambda d: d,
                        lambda out, d, lab: out - lab)
_regression_output_make("MAERegressionOutput", lambda d: d,
                        lambda out, d, lab: jnp.sign(out - lab))
_regression_output_make("LogisticRegressionOutput", jax.nn.sigmoid,
                        lambda out, d, lab: out - lab)


def _make_loss_core_make():
    """Identity forward; backward scales the cotangent by grad_scale with
    the reference's normalization modes (make_loss.cc): 'batch' divides
    by the batch dim, 'valid' by the count of elements whose magnitude
    exceeds valid_thresh."""
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
    def f(data, grad_scale, valid_thresh, normalization):
        return data

    def fwd(data, grad_scale, valid_thresh, normalization):
        valid = None
        if normalization == "valid":
            valid = jnp.maximum(jnp.sum(
                (jnp.abs(data.astype(jnp.float32)) > valid_thresh)
                .astype(jnp.float32)), 1.0)
        return data, valid

    def bwd(grad_scale, valid_thresh, normalization, valid, g):
        gs = grad_scale
        if normalization == "batch":
            gs = gs / g.shape[0]
        grad = g * gs
        if valid is not None:
            grad = grad / valid.astype(g.dtype)
        return (grad,)

    f.defvjp(fwd, bwd)
    return f


_make_loss_core = _make_loss_core_make()


@register("MakeLoss")
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    if float(grad_scale) == 1.0 and normalization == "null":
        return data
    return _make_loss_core(data, float(grad_scale), float(valid_thresh),
                           str(normalization))


# ---------------------------------------------------------------------------
# Dropout (reference: src/operator/nn/dropout.cc) — consumes an RNG key from
# the per-context key table (random=True), preserving mx.random.seed semantics.
# ---------------------------------------------------------------------------
@register("Dropout", random=True)
def _dropout(data, p=0.5, mode="training", axes=None, cudnn_off=None, key=None,
             _training=None):
    """mode='always' applies dropout regardless of train/predict mode
    (reference: dropout.cc DropoutParam mode — enables MC-dropout)."""
    from .. import autograd
    training = _training if _training is not None else autograd.is_training()
    if (not training and mode != "always") or p <= 0.0:
        return data
    shape = list(data.shape)
    if axes:
        for ax in axes:
            shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ---------------------------------------------------------------------------
# Upsampling / grid ops (reference: bilinear_sampler.cc, upsampling.cc,
# grid_generator.cc)
# ---------------------------------------------------------------------------
@register("UpSampling")
def _upsampling(data, *rest, scale=1, sample_type="nearest", num_args=1,
                num_filter=0, multi_input_mode="concat", workspace=None):
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    n, c, h, w = data.shape
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


@register("BilinearSampler")
def _bilinear_sampler(data, grid, cudnn_off=None):
    """reference: src/operator/bilinear_sampler.cc — grid in [-1, 1]."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1 = x0 + 1; y1 = y0 + 1
    wx1 = gx - x0; wy1 = gy - y0
    wx0 = 1 - wx1; wy0 = 1 - wy1

    def gather(img, yy, xx):
        yv = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xv = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
        batch_idx = jnp.arange(n).reshape(n, 1, 1)
        vals = img[batch_idx, :, yv, xv]  # (n, ho, wo, c)
        return vals * valid[..., None]

    out = (gather(data, y0, x0) * (wy0 * wx0)[..., None] +
           gather(data, y0, x1) * (wy0 * wx1)[..., None] +
           gather(data, y1, x0) * (wy1 * wx0)[..., None] +
           gather(data, y1, x1) * (wy1 * wx1)[..., None])
    return jnp.transpose(out, (0, 3, 1, 2))


@register("GridGenerator")
def _grid_generator(data, transform_type="affine", target_shape=None):
    h, w = target_shape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, h*w)
    theta = data.reshape(-1, 2, 3)
    out = jnp.einsum("nij,jk->nik", theta, base)  # (n, 2, h*w)
    return out.reshape(-1, 2, h, w)


@register("ROIPooling")
def _roi_pooling(data, rois, pooled_size=None, spatial_scale=1.0):
    """reference: src/operator/roi_pooling.cc (static-shape adaptation)."""
    ph, pw = pooled_size
    n_rois = rois.shape[0]
    _, c, h, w = data.shape

    def one_roi(roi):
        batch = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        img = data[batch]
        ys = jnp.arange(h); xs = jnp.arange(w)

        def cell(py, px):
            hs = jnp.floor(py * rh / ph).astype(jnp.int32) + y1
            he = jnp.ceil((py + 1) * rh / ph).astype(jnp.int32) + y1
            ws_ = jnp.floor(px * rw / pw).astype(jnp.int32) + x1
            we = jnp.ceil((px + 1) * rw / pw).astype(jnp.int32) + x1
            m = ((ys[None, :, None] >= hs) & (ys[None, :, None] < he) &
                 (xs[None, None, :] >= ws_) & (xs[None, None, :] < we))
            masked = jnp.where(m, img, -jnp.inf)
            v = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        cells = jnp.stack([jnp.stack([cell(py, px) for px in range(pw)])
                           for py in range(ph)])  # (ph, pw, c)
        return jnp.transpose(cells, (2, 0, 1))

    return jax.vmap(one_roi)(rois)


@register("Correlation")
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """FlowNet patch cross-correlation.
    reference: src/operator/correlation.cc (CorrelationOp) — for every
    displacement on a stride2 grid within ±max_displacement, the kernel-
    window patch dot product (or abs-difference) between data1 and shifted
    data2, normalized by kernel²·C. The displacement loop is a static
    Python unroll: D² shifted elementwise products + one box reduction
    each, which XLA fuses — TPU-friendlier than the reference's per-pixel
    CUDA gather."""
    n, c, h, w = data1.shape
    k = int(kernel_size)
    kr = (k - 1) // 2                       # kernel radius
    md, s1, s2 = int(max_displacement), int(stride1), int(stride2)
    pad = int(pad_size)
    border = md + kr
    ph, pw = h + 2 * pad, w + 2 * pad
    out_h = int(_np.ceil((ph - 2 * border) / float(s1)))
    out_w = int(_np.ceil((pw - 2 * border) / float(s1)))
    ngrid = 2 * (md // s2) + 1              # displacements per axis
    sublen = float(k * k * c)

    p1 = jnp.pad(data1.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2.astype(jnp.float32),
                 ((0, 0), (0, 0), (pad, pad), (pad, pad)))

    def box_sum(x):
        # kernel-window sum at every position (valid), summed over C
        if k == 1:
            return jnp.sum(x, axis=1)
        y = lax.reduce_window(x, 0.0, lax.add,
                              (1, 1, k, k), (1, 1, 1, 1), "valid")
        return jnp.sum(y, axis=1)

    maps = []
    a = p1[:, :, md:ph - md, md:pw - md]
    for dy in range(-(md // s2), md // s2 + 1):
        for dx in range(-(md // s2), md // s2 + 1):
            oy, ox = dy * s2, dx * s2
            # data2 window shifted by the displacement; slices span
            # [md, ph-md) so the first valid k-window is CENTERED at
            # border = md + kr, matching the reference's x1 = x·stride1 +
            # max_displacement + kernel_radius indexing
            b = p2[:, :, md + oy:ph - md + oy, md + ox:pw - md + ox]
            prod = a * b if is_multiply else jnp.abs(a - b)
            maps.append(box_sum(prod) / sublen)
    out = jnp.stack(maps, axis=1)           # (n, ngrid², outH', outW')
    out = out[:, :, ::s1, ::s1]
    return out[:, :, :out_h, :out_w].astype(data1.dtype)


# ---------------------------------------------------------------------------
# embedding-bag style & misc
# ---------------------------------------------------------------------------
@register("dot_scaled")
def _dot_scaled(a, b, scale=1.0):
    return scale * jnp.matmul(a, b)


@register("crop")
def _crop(data, *shape_like, offset=None, h_w=None, num_args=1, center_crop=False):
    if shape_like:
        th, tw = shape_like[0].shape[2:4]
    else:
        th, tw = h_w
    h, w = data.shape[2:4]
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    elif offset is not None:
        oy, ox = offset
    else:
        oy = ox = 0
    return data[:, :, oy:oy + th, ox:ox + tw]


alias("crop", "Crop")


# ---------------------------------------------------------------------------
# fused transformer self-attention op surface
# reference: src/operator/contrib/transformer.cc
# (_contrib_interleaved_matmul_selfatt_qk / _valatt, div_sqrt_dim)
# ---------------------------------------------------------------------------
@register("_contrib_div_sqrt_dim")
def _div_sqrt_dim(data):
    """reference: transformer.cc (DivSqrtDim) — x / sqrt(last_dim)."""
    return data / jnp.sqrt(jnp.float32(data.shape[-1])).astype(data.dtype)


def _split_interleaved(qkv, heads, which):
    """(seq, batch, heads*3*hd) interleaved per head -> (batch*heads, seq,
    hd) for which in {0:q, 1:k, 2:v} — the documented equivalent-code
    layout of the reference op."""
    s, b, e = qkv.shape
    hd = e // (heads * 3)
    t = qkv.reshape(s, b, heads, 3, hd)[:, :, :, which, :]
    return t.transpose(1, 2, 0, 3).reshape(b * heads, s, hd)


@register("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_matmul_selfatt_qk(queries_keys_values, heads=None):
    """scores[b*h, q, k] = (q . k) / sqrt(head_dim); one MXU batch-matmul
    straight off the interleaved QKV projection (no materialized
    transpose copies — XLA folds the layout into the dot)."""
    q = _split_interleaved(queries_keys_values, heads, 0)
    k = _split_interleaved(queries_keys_values, heads, 1)
    scale = 1.0 / _np.sqrt(q.shape[-1])
    return jnp.einsum("bqd,bkd->bqk", q * q.dtype.type(scale), k)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                       heads=None):
    """(attention @ v) regrouped to (seq, batch, heads*head_dim)."""
    s, b, e = queries_keys_values.shape
    hd = e // (heads * 3)
    v = _split_interleaved(queries_keys_values, heads, 2)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)
    return (out.reshape(b, heads, s, hd).transpose(2, 0, 1, 3)
            .reshape(s, b, heads * hd))
