"""Pallas sparse-gradient kernels (ISSUE 17 tentpole part 2).

The "Tensor Processing Primitives" single-pass discipline applied to the
scatter-add at the heart of every sparse embedding update: given
``(nnz, D)`` gradient rows and their ``(nnz,)`` row ids, produce the
``(num_segments, D)`` dense accumulation

    out = jnp.zeros((num_segments, D)).at[ids].add(values)

in ONE pass over VMEM tiles. The destination table slab stays resident in
VMEM across the whole grid; each grid step streams one ``(tile, D)`` block
of gradient rows in and folds them into the slab row-by-row (ids ride
SMEM, so the row offset is a scalar load — no gather materialization).
Accumulation order is occurrence order — the same order XLA's
deterministic scatter-add applies duplicate updates — so the kernel is
bit-identical to the composed ``.at[ids].add()`` path (tests assert
equality in interpreter mode).

Dispatch follows the `fused_optimizer` convention exactly:

* gated by ``use_pallas_sparse()`` (interpreter runs always take the
  kernel; compiled runs need the TPU backend + ``MXNET_TPU_USE_PALLAS``);
* ineligible calls (non-float values, int64 ids, empty operands, a
  destination slab that will not fit VMEM) are counted under
  ``ops.pallas.fallback.<reason>`` and routed to the always-correct XLA
  composite — never an error;
* eligible dispatches count ``ops.pallas.dispatch(.segment_sum)`` and
  ride a ``pallas.segment_sum`` telemetry span; ``parse_log --kernels``
  and the new ``parse_log --sparse`` table render the counts.

The op also registers as ``_sparse_segment_sum`` with the Pallas wrapper
as its ``tpu_impl``, so the `registry.best_fn` dispatch surface (the
FCompute<tpu> hook) sees it like every other specialized op.
"""
from __future__ import annotations

import functools
import os

import numpy as _np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_stats as _pstats
from . import registry as _reg
from .pallas_stats import compiler_params as _compiler_params

__all__ = ["segment_sum", "use_pallas_sparse", "scatter_add_rows"]

_LANES = 128
_SUBLANES = 8
_MAX_TILE_NNZ = 512        # 512 x D gradient rows streamed per grid step
_VMEM_BUDGET = 8 << 20     # slab + one tile must fit well under 16 MB


def _interpret():
    return os.environ.get("MXNET_FLASH_INTERPRET", "0") == "1"


def use_pallas_sparse():
    """Is the Pallas sparse path requested? Same gate shape as
    `fused_optimizer.use_pallas_flat`: interpreter runs always take it,
    compiled runs need the TPU backend plus the MXNET_TPU_USE_PALLAS
    opt-in."""
    if _interpret():
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return os.environ.get("MXNET_TPU_USE_PALLAS", "0") == "1"


# ---------------------------------------------------------------------------
# XLA composite — the always-correct reference path
# ---------------------------------------------------------------------------
def _segment_sum_xla(values, ids, num_segments):
    values = jnp.asarray(values)
    ids = jnp.asarray(ids)
    out = jnp.zeros((num_segments,) + values.shape[1:], values.dtype)
    return out.at[ids].add(values)


# ---------------------------------------------------------------------------
# Pallas kernel — destination slab resident in VMEM, gradient rows streamed
# ---------------------------------------------------------------------------
def _kernel_segment_sum(ids_ref, vals_ref, out_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    def body(j, carry):
        row = ids_ref[0, j]

        @pl.when(row >= 0)
        def _add():
            cur = pl.load(out_ref, (pl.ds(row, 1), slice(None)))
            upd = pl.load(vals_ref, (pl.ds(j, 1), slice(None)))
            pl.store(out_ref, (pl.ds(row, 1), slice(None)), cur + upd)
        return carry

    jax.lax.fori_loop(0, ids_ref.shape[1], body, 0)


def _round_up(n, mult):
    return -(-n // mult) * mult


def _segment_sum_pallas_impl(nnz, dim, num_segments, dtype):
    """Build the jittable Pallas launch for one (nnz, dim, num_segments)
    geometry. Shapes are static per trace — the serve/train callers pad to
    fixed bucket sizes, so the trace cache stays small."""
    dim_p = _round_up(max(dim, 1), _LANES)
    seg_p = _round_up(max(num_segments, 1), _SUBLANES)
    tile = min(_MAX_TILE_NNZ, _round_up(max(nnz, 1), _SUBLANES))
    nnz_p = _round_up(max(nnz, 1), tile)
    grid = nnz_p // tile

    def impl(values, ids):
        vals2d = values.reshape(nnz, -1)
        pad_r = nnz_p - nnz
        pad_c = dim_p - vals2d.shape[1]
        if pad_r or pad_c:
            vals2d = jnp.pad(vals2d, ((0, pad_r), (0, pad_c)))
        # pad ids with -1: the kernel skips negative rows, so padding rows
        # never touch the slab
        ids_p = jnp.pad(ids.astype(jnp.int32), (0, pad_r),
                        constant_values=-1).reshape(grid, tile)
        out = pl.pallas_call(
            _kernel_segment_sum,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, tile), lambda i: (i, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((tile, dim_p), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((seg_p, dim_p), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((seg_p, dim_p), dtype),
            compiler_params=_compiler_params(("arbitrary",)),
            interpret=_interpret(),
        )(ids_p, vals2d)
        return out[:num_segments, :dim]
    return impl


_CACHE: dict = {}


def _jitted(key, builder):
    fn = _CACHE.get(key)
    if fn is None:
        fn = _CACHE[key] = jax.jit(builder())
    return fn


def _gate(values, ids, num_segments):
    """Shape/dtype gate. Returns a fallback reason or None."""
    if num_segments <= 0:
        return "empty"
    if int(_np.prod(values.shape, dtype=_np.int64)) == 0:
        return "empty"
    if not jnp.issubdtype(values.dtype, jnp.floating):
        return "dtype"
    if values.ndim < 2:
        return "rank"
    if not jnp.issubdtype(ids.dtype, jnp.integer):
        return "dtype"
    dim = int(_np.prod(values.shape[1:], dtype=_np.int64))
    slab = _round_up(num_segments, _SUBLANES) * _round_up(dim, _LANES)
    tile = min(_MAX_TILE_NNZ, values.shape[0]) * _round_up(dim, _LANES)
    if (slab + tile) * values.dtype.itemsize > _VMEM_BUDGET:
        return "vmem"
    return None


def segment_sum(values, ids, num_segments):
    """Dense scatter-add of sparse rows: ``zeros((num_segments, ...))
    .at[ids].add(values)``, Pallas-fused when eligible. `values` is
    ``(nnz, *row_shape)``, `ids` is ``(nnz,)`` int; rows with negative ids
    are dropped on the kernel path and must not be passed on the XLA path
    (callers pad with a trailing all-zero row instead, or clamp)."""
    values = jnp.asarray(values)
    ids = jnp.asarray(ids)
    if not use_pallas_sparse():
        return _segment_sum_xla(values, ids, num_segments)
    reason = _gate(values, ids, num_segments)
    if reason:
        _pstats.note_fallback("segment_sum", reason)
        return _segment_sum_xla(values, ids, num_segments)
    _pstats.note_dispatch("segment_sum")
    with _pstats.kernel_span("segment_sum"):
        nnz = values.shape[0]
        dim = int(_np.prod(values.shape[1:], dtype=_np.int64))
        fn = _jitted(("segsum", nnz, dim, num_segments, str(values.dtype)),
                     lambda: _segment_sum_pallas_impl(
                         nnz, dim, num_segments, values.dtype))
        out = fn(values, ids)
        return out.reshape((num_segments,) + values.shape[1:])


def scatter_add_rows(table, ids, values):
    """``table.at[ids].add(values)`` through the same dispatch: the
    segment-sum produces the dense delta for the table's leading axis and
    one vector add applies it. Used by the embedding update path so the
    scatter rides the kernel without a separate gather."""
    table = jnp.asarray(table)
    delta = segment_sum(jnp.asarray(values), ids, table.shape[0])
    return table + delta.astype(table.dtype)


# ---------------------------------------------------------------------------
# registry surface — the FCompute<tpu> hook
# ---------------------------------------------------------------------------
@_reg.register("_sparse_segment_sum", arity=2, differentiable=False,
               doc="dense scatter-add of (ids, values) rows into a "
                   "num_segments-row table")
def _sparse_segment_sum(values, ids, num_segments=0):
    return _segment_sum_xla(values, ids, int(num_segments))


@_reg.get("_sparse_segment_sum").tpu_impl
def _sparse_segment_sum_tpu(values, ids, num_segments=0):
    return segment_sum(values, ids, int(num_segments))
