"""Fused conv3x3 + folded-BN + ReLU (+ residual) — the ROOFLINE.md fusion
project.

reference contrast: the reference gets this fusion from cuDNN's fused
conv-bias-activation path and its RTC pointwise fuser (SURVEY §2.1); on
TPU the XLA path already fuses the BN affine + ReLU into the conv's
epilogue, but each op boundary still round-trips activations through HBM
in the NCHW layout benchmark. This op is the explicit fused form: one
`_contrib_conv_bn_relu` node whose TPU implementation is a Pallas
implicit-GEMM kernel — the 3x3 conv becomes 9 shifted (H·W, Cin) x
(Cin, Cout-block) MXU dots accumulated in VMEM, and the scale/shift/ReLU
/residual epilogue runs on the accumulator before it ever leaves VMEM.

Layout NHWC (the TPU-native channels-last layout), stride 1, SAME pad —
the shape of every interior ResNet block conv. BN is the FOLDED
(inference) form: scale = gamma/sqrt(var+eps), shift = beta - mean*scale;
`fold_bn_params` computes them from a Gluon BatchNorm's tensors. Training
keeps the composed conv/BatchNorm ops (batch statistics need the conv
output before normalization can start).

Enable the Pallas path with MXNET_TPU_USE_PALLAS=1 (registry tpu_impl
gate); MXNET_FLASH_INTERPRET=1 runs it through the interpreter on CPU for
the test suite.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_stats as _pstats
from .registry import register, get

__all__ = ["fold_bn_params"]


def _interpret():
    return os.environ.get("MXNET_FLASH_INTERPRET", "0") == "1"


# version-tolerant Mosaic params shim — shared by every kernel module
_compiler_params = _pstats.compiler_params


def fold_bn_params(gamma, beta, moving_mean, moving_var, eps=1e-3):
    """BN(inference) == y*scale + shift with these folded tensors."""
    scale = gamma / jnp.sqrt(moving_var + eps)
    return scale, beta - moving_mean * scale


def _conv3x3_same(x, w):
    """The one conv config this module fuses: 3x3, stride 1, SAME, NHWC,
    f32 accumulation. Single definition — the training forward, its
    backward, and the inference path must never desynchronize."""
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)


def _xla_conv_bn_relu(x, w, scale, shift, residual=None):
    """Reference XLA path: lax conv in NHWC + affine + relu."""
    out = _conv3x3_same(x, w)
    out = out * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if residual is not None:
        out = out + residual.astype(jnp.float32)
    return jnp.maximum(out, 0.0).astype(x.dtype)


def _kernel(x_ref, w_ref, s_ref, b_ref, *rest, block_co, H, W, C,
            has_residual):
    if has_residual:
        r_ref, o_ref = rest
    else:
        (o_ref,) = rest
    x = x_ref[0].astype(jnp.float32)            # (H, W, C)
    acc = jnp.zeros((H * W, block_co), jnp.float32)
    # implicit GEMM: 9 shifted full-image dots, accumulator stays in VMEM
    for dh in (-1, 0, 1):
        for dw in (-1, 0, 1):
            shifted = jnp.roll(x, (-dh, -dw), axis=(0, 1))
            rows = lax.broadcasted_iota(jnp.int32, (H, W), 0)
            cols = lax.broadcasted_iota(jnp.int32, (H, W), 1)
            valid = ((rows + dh >= 0) & (rows + dh < H) &
                     (cols + dw >= 0) & (cols + dw < W))
            shifted = jnp.where(valid[..., None], shifted, 0.0)
            wk = w_ref[dh + 1, dw + 1].astype(jnp.float32)   # (C, bco)
            acc += jax.lax.dot_general(
                shifted.reshape(H * W, C), wk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    out = acc * s_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    if has_residual:
        out = out + r_ref[0].astype(jnp.float32).reshape(H * W, block_co)
    out = jnp.maximum(out, 0.0)
    o_ref[0] = out.reshape(H, W, block_co).astype(o_ref.dtype)


def _pallas_conv_bn_relu(x, w, scale, shift, residual=None, block_co=128):
    N, H, W, C = x.shape
    Cout = w.shape[-1]
    block_co = min(block_co, Cout)
    n_co = pl.cdiv(Cout, block_co)
    has_res = residual is not None

    cparams = _compiler_params(("parallel", "parallel"))

    in_specs = [
        pl.BlockSpec((1, H, W, C), lambda n, c: (n, 0, 0, 0)),
        pl.BlockSpec((3, 3, C, block_co), lambda n, c: (0, 0, 0, c)),
        pl.BlockSpec((block_co,), lambda n, c: (c,)),
        pl.BlockSpec((block_co,), lambda n, c: (c,)),
    ]
    args = [x, w, scale, shift]
    if has_res:
        in_specs.append(pl.BlockSpec((1, H, W, block_co),
                                     lambda n, c: (n, 0, 0, c)))
        args.append(residual)

    out = pl.pallas_call(
        functools.partial(_kernel, block_co=block_co, H=H, W=W, C=C,
                          has_residual=has_res),
        grid=(N, n_co),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, W, block_co),
                               lambda n, c: (n, 0, 0, c)),
        out_shape=jax.ShapeDtypeStruct((N, H, W, Cout), x.dtype),
        interpret=_interpret(),
        **({"compiler_params": cparams} if cparams else {}),
    )(*args)
    return out


def _shapes_ok(x, w):
    C, Cout = x.shape[-1], w.shape[-1]
    return (w.shape[0] == 3 and w.shape[1] == 3 and
            C % 8 == 0 and Cout % 8 == 0)


# inference-path op: differentiable=False — the Pallas kernel has no AD
# rule, and training keeps the composed Conv/BatchNorm ops anyway (batch
# statistics need the conv output before normalization)
@register("_contrib_conv_bn_relu", arity=None, differentiable=False)
def _conv_bn_relu(x, w, scale, shift, *residual):
    """x (N,H,W,C) NHWC; w (3,3,Cin,Cout) HWIO; scale/shift (Cout,);
    optional residual (N,H,W,Cout). Stride 1, SAME pad, folded-BN + ReLU
    epilogue."""
    res = residual[0] if residual else None
    return _xla_conv_bn_relu(x, w, scale, shift, res)


# the Pallas kernel registers through tpu_impl so the registry's
# MXNET_TPU_USE_PALLAS kill switch (registry.best_fn) really gates it
@get("_contrib_conv_bn_relu").tpu_impl
def _conv_bn_relu_tpu(x, w, scale, shift, *residual):
    res = residual[0] if residual else None
    if not _shapes_ok(x, w):
        _pstats.note_fallback("cbr_infer", "shape")
        return _xla_conv_bn_relu(x, w, scale, shift, res)
    _pstats.note_dispatch("cbr_infer")
    with _pstats.kernel_span("cbr_infer"):
        return _pallas_conv_bn_relu(x, w, scale, shift, res)


# ---------------------------------------------------------------------------
# TRAINING-form fusion (round-4 VERDICT weak #3 / round-5 task 2): batch
# statistics need the conv output, so training is a two-pass structure.
# The composed XLA graph pays (at least) four HBM passes over the conv
# output: write it, read it for the stats reduction, read it again for the
# normalize, write the activation. The fused form computes the stats IN
# THE CONV EPILOGUE from the f32 VMEM accumulator (pass 1 writes conv_out
# once and emits per-grid-cell partial sums — the stats reduction never
# re-reads conv_out from HBM), then one elementwise normalize pass.
# Backward recomputes xhat from conv_out + saved stats (no xhat/mask
# materialization in forward) and rides XLA's transposed convs for dx/dw.
# reference contrast: cuDNN's fused conv-bias-act serves training in the
# reference (SURVEY §2.1 cuDNN row); its BN backward fusions are
# cudnnBatchNormalizationBackwardEx.
# ---------------------------------------------------------------------------
def _stats_block_co(Cout, cap=128):
    """Largest multiple-of-8 divisor of Cout up to `cap` (partial-stat
    slabs must tile Cout exactly)."""
    best = 0
    for b in range(8, min(cap, Cout) + 1, 8):
        if Cout % b == 0:
            best = b
    return best


def _kernel_train(x_ref, w_ref, o_ref, p_ref, *, block_co, H, W, C):
    """Conv pass with stats epilogue: writes the conv output AND this grid
    cell's per-channel (sum, sum-of-squares) computed from the f32
    accumulator while it is still in VMEM."""
    x = x_ref[0].astype(jnp.float32)            # (H, W, C)
    acc = jnp.zeros((H * W, block_co), jnp.float32)
    for dh in (-1, 0, 1):
        for dw in (-1, 0, 1):
            shifted = jnp.roll(x, (-dh, -dw), axis=(0, 1))
            rows = lax.broadcasted_iota(jnp.int32, (H, W), 0)
            cols = lax.broadcasted_iota(jnp.int32, (H, W), 1)
            valid = ((rows + dh >= 0) & (rows + dh < H) &
                     (cols + dw >= 0) & (cols + dw < W))
            shifted = jnp.where(valid[..., None], shifted, 0.0)
            wk = w_ref[dh + 1, dw + 1].astype(jnp.float32)   # (C, bco)
            acc += jax.lax.dot_general(
                shifted.reshape(H * W, C), wk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    o_ref[0] = acc.reshape(H, W, block_co).astype(o_ref.dtype)
    p_ref[0, 0, 0] = jnp.sum(acc, axis=0)
    p_ref[0, 0, 1] = jnp.sum(acc * acc, axis=0)


def _pallas_conv_stats(x, w):
    """Pass 1: conv_out (x.dtype) + f32 per-channel (sum, sumsq)."""
    N, H, W, C = x.shape
    Cout = w.shape[-1]
    block_co = _stats_block_co(Cout)
    n_co = Cout // block_co

    cparams = _compiler_params(("parallel", "parallel"))

    conv_out, partial = pl.pallas_call(
        functools.partial(_kernel_train, block_co=block_co, H=H, W=W, C=C),
        grid=(N, n_co),
        in_specs=[
            pl.BlockSpec((1, H, W, C), lambda n, c: (n, 0, 0, 0)),
            pl.BlockSpec((3, 3, C, block_co), lambda n, c: (0, 0, 0, c)),
        ],
        out_specs=[
            pl.BlockSpec((1, H, W, block_co), lambda n, c: (n, 0, 0, c)),
            pl.BlockSpec((1, 1, 2, block_co), lambda n, c: (n, c, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, H, W, Cout), x.dtype),
            jax.ShapeDtypeStruct((N, n_co, 2, block_co), jnp.float32),
        ],
        interpret=_interpret(),
        **({"compiler_params": cparams} if cparams else {}),
    )(x, w)
    # (N, n_co, 2, bco) -> (2, Cout); tiny host-side reduction
    sums = partial.transpose(2, 1, 3, 0).reshape(2, Cout, N).sum(axis=-1)
    return conv_out, sums[0], sums[1]


def _xla_conv_stats(x, w):
    conv_out = _conv3x3_same(x, w)
    s = jnp.sum(conv_out, axis=(0, 1, 2))
    sq = jnp.sum(conv_out * conv_out, axis=(0, 1, 2))
    return conv_out.astype(x.dtype), s, sq


def _pallas_train_gate():
    """Is the Pallas training path REQUESTED (independent of shapes)?
    Interpreter runs always request it (that is what they test); compiled
    runs need the TPU backend plus the MXNET_TPU_USE_PALLAS opt-in."""
    if _interpret():
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return os.environ.get("MXNET_TPU_USE_PALLAS", "0") == "1"


def _use_pallas_train(x, w):
    if not _pallas_train_gate():
        return False
    return bool(_shapes_ok(x, w) and _stats_block_co(w.shape[-1]))


def _normalize_relu(conv_out, mean, invstd, gamma, beta, residual):
    xhat = (conv_out.astype(jnp.float32) - mean) * invstd
    y = xhat * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    return xhat, y


def _cbr_train_compute(eps, x, w, gamma, beta, residual):
    """Shared forward: pass-1 conv+stats, pass-2 normalize+relu."""
    if _use_pallas_train(x, w):
        _pstats.note_dispatch("cbr_train_fwd")
        with _pstats.kernel_span("cbr_train_fwd"):
            conv_out, s, sq = _pallas_conv_stats(x, w)
    else:
        if _pallas_train_gate():
            _pstats.note_fallback("cbr_train_fwd", "shape")
        conv_out, s, sq = _xla_conv_stats(x, w)
    M = x.shape[0] * x.shape[1] * x.shape[2]
    mean = s / M
    var = jnp.maximum(sq / M - mean * mean, 0.0)
    invstd = lax.rsqrt(var + eps)
    _, y = _normalize_relu(conv_out, mean, invstd, gamma, beta, residual)
    out = jnp.maximum(y, 0.0).astype(x.dtype)
    return out, mean, var, invstd, conv_out


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _cbr_train(eps, has_res, x, w, gamma, beta, residual):
    out, mean, var, _, _ = _cbr_train_compute(eps, x, w, gamma, beta,
                                              residual)
    return out, mean, var


def _cbr_train_fwd_rule(eps, has_res, x, w, gamma, beta, residual):
    out, mean, var, invstd, conv_out = _cbr_train_compute(
        eps, x, w, gamma, beta, residual)
    return (out, mean, var), (x, w, conv_out, mean, invstd, gamma, beta,
                              residual)


# ---------------------------------------------------------------------------
# FUSED BACKWARD (round-6 / ISSUE 10 tentpole): the composed backward
# recomputes xhat/the relu mask and runs its per-channel reductions (dgamma,
# dbeta, the two Σdxhat moments) plus the dconv elementwise pass as separate
# XLA loops — each re-reading conv_out and dy from HBM. `_kernel_train_bwd`
# is ONE pallas_call over grid (co_block, phase, n):
#
#   phase 0  streams every (n, co) tile of conv_out/dy once, recomputes
#            xhat and the relu mask IN VMEM, and accumulates the two
#            per-channel reductions (Σg = dbeta, Σg·xhat = dgamma) in a
#            VMEM scratch accumulator — the only full reductions the BN
#            backward needs (the dxhat moments are gamma·Σg and
#            gamma·Σg·xhat, derived in-register);
#   phase 1  streams the tiles a second time (the data dependency of
#            dconv on the global sums makes a second streaming pass the
#            information-theoretic minimum — nothing is ever
#            materialized between the passes) and emits the dconv tiles
#            (+ dres = masked dy when the block has a residual input).
#
# HBM traffic: 2×(conv_out + dy [+ residual]) reads + 1×dconv (+dres)
# write + O(C) stats. The composed program additionally materializes (or
# re-derives through separate fusions) xhat and the pre-relu activation.
# The phase-0 visits of the dconv/dres output map to block (0, c) and
# write nothing, so no garbage tile ever rides back to HBM.
# dx/dw still ride XLA's transposed convs — those are MXU-optimal.
# ---------------------------------------------------------------------------
def _kernel_train_bwd(co_ref, dy_ref, m_ref, i_ref, g_ref, b_ref, *rest,
                      block_co, H, W, N, M, has_residual):
    if has_residual:
        r_ref, dco_ref, dg_ref, db_ref, dr_ref, acc = rest
    else:
        dco_ref, dg_ref, db_ref, acc = rest
    phase = pl.program_id(1)
    n = pl.program_id(2)
    conv = co_ref[0].astype(jnp.float32).reshape(H * W, block_co)
    dy = dy_ref[0].astype(jnp.float32).reshape(H * W, block_co)
    mean = m_ref[...].astype(jnp.float32)
    invstd = i_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)
    xhat = (conv - mean) * invstd
    y = xhat * gamma + b_ref[...].astype(jnp.float32)
    if has_residual:
        y = y + r_ref[0].astype(jnp.float32).reshape(H * W, block_co)
    g = jnp.where(y > 0, dy, 0.0)

    @pl.when(phase == 0)
    def _():
        @pl.when(n == 0)
        def _():
            acc[...] = jnp.zeros_like(acc)
        acc[0, :] += jnp.sum(g, axis=0)
        acc[1, :] += jnp.sum(g * xhat, axis=0)

        @pl.when(n == N - 1)
        def _():
            db_ref[...] = acc[0, :]
            dg_ref[...] = acc[1, :]

    @pl.when(phase == 1)
    def _():
        dxhat = g * gamma
        mean_dxhat = gamma * (acc[0, :] / M)
        mean_dxhat_xhat = gamma * (acc[1, :] / M)
        dconv = invstd * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
        dco_ref[0] = dconv.reshape(H, W, block_co)
        if has_residual:
            dr_ref[0] = g.reshape(H, W, block_co).astype(dr_ref.dtype)


def _pallas_cbr_bwd(conv_out, dy, mean, invstd, gamma, beta, residual=None):
    """One fused backward launch: (dconv f32, dgamma f32, dbeta f32
    [, dres residual-dtype]) from conv_out + dy + saved stats."""
    N, H, W, Cout = conv_out.shape
    block_co = _stats_block_co(Cout)
    n_co = Cout // block_co
    has_res = residual is not None

    cparams = _compiler_params(("arbitrary", "arbitrary", "arbitrary"))

    tile = pl.BlockSpec((1, H, W, block_co), lambda c, p, n: (n, 0, 0, c))
    chan = pl.BlockSpec((block_co,), lambda c, p, n: (c,))
    # phase-0 visits of the elementwise outputs park on block (0, c):
    # consecutive same-index visits never copy out, so the only HBM write
    # is phase 1's real tile
    out_tile = pl.BlockSpec((1, H, W, block_co),
                            lambda c, p, n: (n * p, 0, 0, c))
    in_specs = [tile, tile, chan, chan, chan, chan]
    args = [conv_out, dy, mean, invstd, gamma, beta]
    out_specs = [out_tile, chan, chan]
    out_shapes = [jax.ShapeDtypeStruct((N, H, W, Cout), jnp.float32),
                  jax.ShapeDtypeStruct((Cout,), jnp.float32),
                  jax.ShapeDtypeStruct((Cout,), jnp.float32)]
    if has_res:
        in_specs.append(tile)
        args.append(residual)
        out_specs.append(out_tile)
        out_shapes.append(
            jax.ShapeDtypeStruct((N, H, W, Cout), residual.dtype))

    outs = pl.pallas_call(
        functools.partial(_kernel_train_bwd, block_co=block_co, H=H, W=W,
                          N=N, M=float(N * H * W), has_residual=has_res),
        grid=(n_co, 2, N),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((2, block_co), jnp.float32)],
        interpret=_interpret(),
        **({"compiler_params": cparams} if cparams else {}),
    )(*args)
    dconv, dgamma, dbeta = outs[:3]
    dres = outs[3] if has_res else None
    return dconv, dgamma, dbeta, dres


def _xla_cbr_bwd(conv_out, dy, mean, invstd, gamma, beta, residual=None):
    """Composite backward epilogue (the pre-round-6 path, and the escape
    hatch): recompute xhat/mask, three reductions, dconv pass — all as
    separate XLA ops over HBM-resident tensors."""
    g_out = dy.astype(jnp.float32)
    xhat, y = _normalize_relu(conv_out, mean, invstd, gamma, beta, residual)
    g = jnp.where(y > 0, g_out, 0.0)
    axes = (0, 1, 2)
    dbeta = jnp.sum(g, axis=axes)
    dgamma = jnp.sum(g * xhat, axis=axes)
    dxhat = g * gamma.astype(jnp.float32)
    mean_dxhat = jnp.mean(dxhat, axis=axes)
    mean_dxhat_xhat = jnp.mean(dxhat * xhat, axis=axes)
    dconv = invstd * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    dres = g.astype(residual.dtype) if residual is not None else None
    return dconv, dgamma, dbeta, dres


def _cbr_train_bwd_rule(eps, has_res, saved, cots):
    x, w, conv_out, mean, invstd, gamma, beta, residual = saved
    # mean/var cotangents are dropped: running-stat updates are stop-grad
    # (reference BatchNorm semantics)
    if _use_pallas_train(x, w):
        _pstats.note_dispatch("cbr_train_bwd")
        with _pstats.kernel_span("cbr_train_bwd"):
            dconv, dgamma, dbeta, dres = _pallas_cbr_bwd(
                conv_out, cots[0], mean, invstd, gamma, beta,
                residual if has_res else None)
    else:
        if _pallas_train_gate():
            _pstats.note_fallback("cbr_train_bwd", "shape")
        dconv, dgamma, dbeta, dres = _xla_cbr_bwd(
            conv_out, cots[0], mean, invstd, gamma, beta,
            residual if has_res else None)

    _, conv_vjp = jax.vjp(_conv3x3_same, x.astype(jnp.float32),
                          w.astype(jnp.float32))
    dx, dw = conv_vjp(dconv)
    return (dx.astype(x.dtype), dw.astype(w.dtype),
            dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            dres if has_res else None)


_cbr_train.defvjp(_cbr_train_fwd_rule, _cbr_train_bwd_rule)


@register("_contrib_conv_bn_relu_train", arity=None, num_outputs=3)
def _conv_bn_relu_train(x, w, gamma, beta, *residual, eps=1e-3):
    """Training-form fused conv3x3 + BatchNorm + ReLU (+ residual).

    x (N,H,W,C) NHWC; w (3,3,Cin,Cout) HWIO; gamma/beta (Cout,);
    optional residual (N,H,W,Cout). Returns (out, batch_mean, batch_var)
    — the caller updates running stats from mean/var exactly like
    BatchNorm does; gradients flow to x/w/gamma/beta/residual through the
    standard training-BN backward (mean/var outputs carry stop-grad,
    reference BatchNorm semantics).
    """
    res = residual[0] if residual else None
    return _cbr_train(eps, res is not None, x, w, gamma, beta, res)
