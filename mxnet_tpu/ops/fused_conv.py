"""Fused conv3x3 + folded-BN + ReLU (+ residual) — the ROOFLINE.md fusion
project.

reference contrast: the reference gets this fusion from cuDNN's fused
conv-bias-activation path and its RTC pointwise fuser (SURVEY §2.1); on
TPU the XLA path already fuses the BN affine + ReLU into the conv's
epilogue, but each op boundary still round-trips activations through HBM
in the NCHW layout benchmark. This op is the explicit fused form: one
`_contrib_conv_bn_relu` node whose TPU implementation is a Pallas
implicit-GEMM kernel — the 3x3 conv becomes 9 shifted (H·W, Cin) x
(Cin, Cout-block) MXU dots accumulated in VMEM, and the scale/shift/ReLU
/residual epilogue runs on the accumulator before it ever leaves VMEM.

Layout NHWC (the TPU-native channels-last layout), stride 1, SAME pad —
the shape of every interior ResNet block conv. BN is the FOLDED
(inference) form: scale = gamma/sqrt(var+eps), shift = beta - mean*scale;
`fold_bn_params` computes them from a Gluon BatchNorm's tensors. Training
keeps the composed conv/BatchNorm ops (batch statistics need the conv
output before normalization can start).

Enable the Pallas path with MXNET_TPU_USE_PALLAS=1 (registry tpu_impl
gate); MXNET_FLASH_INTERPRET=1 runs it through the interpreter on CPU for
the test suite.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register, get

__all__ = ["fold_bn_params"]


def _interpret():
    return os.environ.get("MXNET_FLASH_INTERPRET", "0") == "1"


def fold_bn_params(gamma, beta, moving_mean, moving_var, eps=1e-3):
    """BN(inference) == y*scale + shift with these folded tensors."""
    scale = gamma / jnp.sqrt(moving_var + eps)
    return scale, beta - moving_mean * scale


def _xla_conv_bn_relu(x, w, scale, shift, residual=None):
    """Reference XLA path: lax conv in NHWC + affine + relu."""
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    out = out * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if residual is not None:
        out = out + residual.astype(jnp.float32)
    return jnp.maximum(out, 0.0).astype(x.dtype)


def _kernel(x_ref, w_ref, s_ref, b_ref, *rest, block_co, H, W, C,
            has_residual):
    if has_residual:
        r_ref, o_ref = rest
    else:
        (o_ref,) = rest
    x = x_ref[0].astype(jnp.float32)            # (H, W, C)
    acc = jnp.zeros((H * W, block_co), jnp.float32)
    # implicit GEMM: 9 shifted full-image dots, accumulator stays in VMEM
    for dh in (-1, 0, 1):
        for dw in (-1, 0, 1):
            shifted = jnp.roll(x, (-dh, -dw), axis=(0, 1))
            rows = lax.broadcasted_iota(jnp.int32, (H, W), 0)
            cols = lax.broadcasted_iota(jnp.int32, (H, W), 1)
            valid = ((rows + dh >= 0) & (rows + dh < H) &
                     (cols + dw >= 0) & (cols + dw < W))
            shifted = jnp.where(valid[..., None], shifted, 0.0)
            wk = w_ref[dh + 1, dw + 1].astype(jnp.float32)   # (C, bco)
            acc += jax.lax.dot_general(
                shifted.reshape(H * W, C), wk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    out = acc * s_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    if has_residual:
        out = out + r_ref[0].astype(jnp.float32).reshape(H * W, block_co)
    out = jnp.maximum(out, 0.0)
    o_ref[0] = out.reshape(H, W, block_co).astype(o_ref.dtype)


def _pallas_conv_bn_relu(x, w, scale, shift, residual=None, block_co=128):
    N, H, W, C = x.shape
    Cout = w.shape[-1]
    block_co = min(block_co, Cout)
    n_co = pl.cdiv(Cout, block_co)
    has_res = residual is not None

    try:
        cparams = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"))
    except TypeError:
        cparams = None

    in_specs = [
        pl.BlockSpec((1, H, W, C), lambda n, c: (n, 0, 0, 0)),
        pl.BlockSpec((3, 3, C, block_co), lambda n, c: (0, 0, 0, c)),
        pl.BlockSpec((block_co,), lambda n, c: (c,)),
        pl.BlockSpec((block_co,), lambda n, c: (c,)),
    ]
    args = [x, w, scale, shift]
    if has_res:
        in_specs.append(pl.BlockSpec((1, H, W, block_co),
                                     lambda n, c: (n, 0, 0, c)))
        args.append(residual)

    out = pl.pallas_call(
        functools.partial(_kernel, block_co=block_co, H=H, W=W, C=C,
                          has_residual=has_res),
        grid=(N, n_co),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, W, block_co),
                               lambda n, c: (n, 0, 0, c)),
        out_shape=jax.ShapeDtypeStruct((N, H, W, Cout), x.dtype),
        interpret=_interpret(),
        **({"compiler_params": cparams} if cparams else {}),
    )(*args)
    return out


def _shapes_ok(x, w):
    C, Cout = x.shape[-1], w.shape[-1]
    return (w.shape[0] == 3 and w.shape[1] == 3 and
            C % 8 == 0 and Cout % 8 == 0)


# inference-path op: differentiable=False — the Pallas kernel has no AD
# rule, and training keeps the composed Conv/BatchNorm ops anyway (batch
# statistics need the conv output before normalization)
@register("_contrib_conv_bn_relu", arity=None, differentiable=False)
def _conv_bn_relu(x, w, scale, shift, *residual):
    """x (N,H,W,C) NHWC; w (3,3,Cin,Cout) HWIO; scale/shift (Cout,);
    optional residual (N,H,W,Cout). Stride 1, SAME pad, folded-BN + ReLU
    epilogue."""
    res = residual[0] if residual else None
    return _xla_conv_bn_relu(x, w, scale, shift, res)


# the Pallas kernel registers through tpu_impl so the registry's
# MXNET_TPU_USE_PALLAS kill switch (registry.best_fn) really gates it
@get("_contrib_conv_bn_relu").tpu_impl
def _conv_bn_relu_tpu(x, w, scale, shift, *residual):
    res = residual[0] if residual else None
    if not _shapes_ok(x, w):
        return _xla_conv_bn_relu(x, w, scale, shift, res)
    return _pallas_conv_bn_relu(x, w, scale, shift, res)
