"""Fused recurrent ops.

TPU-native analog of reference src/operator/rnn.cc / rnn-inl.h (the fused
`sym.RNN` op that dispatches to cuDNN). Here each layer/direction is one
`jax.lax.scan` over the time axis — XLA compiles the step body once and the
scan keeps the whole sequence on-device (the TPU analog of cuDNN's fused
RNN). Layouts and the flat-parameter vector format match the reference:

* data: TNC (seq_len, batch, input)
* parameters: single flat vector — all weights (per layer, per direction:
  i2h then h2h), then all biases in the same order.
* gate order: LSTM [i, f, g, o], GRU [r, z, n] — cuDNN order, as in the
  reference (rnn-inl.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _cell_step(mode):
    if mode == "rnn_relu":
        def step(x_proj, h, c, h2h_w, h2h_b):
            return jax.nn.relu(x_proj + h @ h2h_w.T + h2h_b), c
    elif mode == "rnn_tanh":
        def step(x_proj, h, c, h2h_w, h2h_b):
            return jnp.tanh(x_proj + h @ h2h_w.T + h2h_b), c
    elif mode == "lstm":
        def step(x_proj, h, c, h2h_w, h2h_b):
            g = x_proj + h @ h2h_w.T + h2h_b
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            gg = jnp.tanh(gg)
            o = jax.nn.sigmoid(o)
            c_new = f * c + i * gg
            return o * jnp.tanh(c_new), c_new
    elif mode == "gru":
        def step(x_proj, h, c, h2h_w, h2h_b):
            # x_proj = x @ i2h_w.T + i2h_b, gates [r, z, n]
            hp = h @ h2h_w.T + h2h_b
            xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h, c
    else:
        raise ValueError("unknown RNN mode " + mode)
    return step


def rnn_param_size(mode, input_size, state_size, num_layers,
                   bidirectional=False):
    """Total length of the packed parameter vector for the layout
    `_slice_params` defines — the single source of truth used by shape
    inference (ops/shape_hints.py) and initializer.FusedRNN."""
    ng = _gates(mode)
    h = state_size
    ndir = 2 if bidirectional else 1
    n = ndir * ng * h * (input_size + h) \
        + (num_layers - 1) * ndir * ng * h * (h * ndir + h) \
        + num_layers * ndir * 2 * ng * h
    return n


def rnn_solve_input_size(mode, total, state_size, num_layers,
                         bidirectional=False):
    """Invert rnn_param_size for the input size; raises if `total` is not
    a valid packed-vector length for these hyper-params."""
    ng = _gates(mode)
    h = state_size
    ndir = 2 if bidirectional else 1
    L = num_layers
    bias_total = L * ndir * 2 * ng * h
    deeper = (L - 1) * ndir * ng * h * (h * ndir + h)
    in_sz = (total - bias_total - deeper) // (ndir * ng * h) - h
    if in_sz <= 0 or rnn_param_size(mode, in_sz, h, L,
                                    bidirectional) != total:
        raise ValueError(
            "cannot solve input size from a %d-element packed RNN "
            "parameter vector (mode=%s, %d hidden, %d layers)"
            % (total, mode, h, L))
    return in_sz


def _slice_params(params, mode, input_size, state_size, num_layers,
                  bidirectional, projection_size=None):
    """Carve the flat parameter vector into per-layer weights, matching the
    reference layout (rnn-inl.h: all weights then all biases)."""
    ng = _gates(mode)
    ndir = 2 if bidirectional else 1
    h = state_size
    layers = []
    off = 0
    for layer in range(num_layers):
        for d in range(ndir):
            in_sz = input_size if layer == 0 else h * ndir
            i2h_n = ng * h * in_sz
            h2h_n = ng * h * h
            layers.append({"i2h_w": (off, (ng * h, in_sz))})
            off += i2h_n
            layers[-1]["h2h_w"] = (off, (ng * h, h))
            off += h2h_n
    for idx in range(num_layers * ndir):
        layers[idx]["i2h_b"] = (off, (ng * h,))
        off += ng * h
        layers[idx]["h2h_b"] = (off, (ng * h,))
        off += ng * h
    out = []
    for spec in layers:
        entry = {}
        for k, (o, shape) in spec.items():
            n = 1
            for s in shape:
                n *= s
            entry[k] = lax.dynamic_slice(params, (o,), (n,)).reshape(shape)
        out.append(entry)
    return out


@register("RNN", num_outputs=3, random=True)
def _rnn(data, parameters, state, state_cell=None, state_size=None,
         num_layers=1, mode="lstm", bidirectional=False, p=0.0,
         state_outputs=False, projection_size=None, use_sequence_length=False,
         sequence_length=None, lstm_state_clip_min=None,
         lstm_state_clip_max=None, lstm_state_clip_nan=False, key=None,
         _training=None):
    """Fused multi-layer (bi)RNN/LSTM/GRU. reference: src/operator/rnn.cc.

    Returns (output[TND], state_n, cell_n) — callers that asked for fewer
    outputs slice the tuple (state_cell only meaningful for lstm)."""
    from .. import autograd
    training = _training if _training is not None else autograd.is_training()
    T, N, input_size = data.shape
    h = state_size
    ndir = 2 if bidirectional else 1
    specs = _slice_params(parameters, mode, input_size, h, num_layers,
                          bidirectional, projection_size)
    step_fn = _cell_step(mode)

    x = data
    out_states = []
    out_cells = []
    for layer in range(num_layers):
        dir_outputs = []
        for d in range(ndir):
            spec = specs[layer * ndir + d]
            h0 = state[layer * ndir + d]
            c0 = state_cell[layer * ndir + d] if (
                mode == "lstm" and state_cell is not None) else \
                jnp.zeros_like(h0)
            seq = x if d == 0 else jnp.flip(x, axis=0)
            x_proj = jnp.einsum("tni,gi->tng", seq, spec["i2h_w"]) + \
                spec["i2h_b"]

            def scan_body(carry, xp):
                hh, cc = carry
                hh, cc = step_fn(xp, hh, cc, spec["h2h_w"], spec["h2h_b"])
                if mode == "lstm" and lstm_state_clip_min is not None:
                    cc = jnp.clip(cc, lstm_state_clip_min,
                                  lstm_state_clip_max)
                return (hh, cc), hh

            (hT, cT), ys = lax.scan(scan_body, (h0, c0), x_proj)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outputs.append(ys)
            out_states.append(hT)
            out_cells.append(cT)
        x = dir_outputs[0] if ndir == 1 else jnp.concatenate(dir_outputs,
                                                             axis=-1)
        if p > 0 and training and layer < num_layers - 1 and key is not None:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape).astype(x.dtype)
            x = x * mask / (1 - p)
    state_n = jnp.stack(out_states, axis=0)
    cell_n = jnp.stack(out_cells, axis=0)
    return x, state_n, cell_n
