"""Tensor ops: elementwise, broadcast, reductions, shape manipulation, indexing.

TPU-native analog of the reference's src/operator/tensor/* op families
(reference: elemwise_unary_op_basic.cc, elemwise_binary_broadcast_op_basic.cc,
broadcast_reduce_op_value.cc, matrix_op.cc, indexing_op.cc, dot.cc,
control_flow_op.cc, ordering_op.cc). Implementations are jax.numpy/lax
compositions — XLA fuses elementwise chains natively, which is what the
reference's mshadow expression templates + NVRTC pointwise fusion existed to do
(SURVEY.md §2.1), so there is no per-op kernel code here by design.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias, get as _get_op
from ..base import get_env, np_dtype

_f32 = jnp.float32


def _safe_acc_dtype(x):
    """reference: MXNET_SAFE_ACCUMULATION — accumulate small floats in fp32."""
    if get_env("MXNET_SAFE_ACCUMULATION") and x.dtype in (jnp.float16, jnp.bfloat16):
        return _f32
    return None


def _norm_axis(axis, exclude=False, ndim=None):
    if axis is None:
        ax = None
    elif isinstance(axis, int):
        ax = (axis,)
    else:
        ax = tuple(axis)
    if exclude and ax is not None:
        ax = tuple(i for i in range(ndim) if i not in
                   tuple(a % ndim for a in ax))
    return ax


# ---------------------------------------------------------------------------
# unary elementwise (reference: src/operator/tensor/elemwise_unary_op_basic.cc)
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "negative": jnp.negative,
    "reciprocal": jnp.reciprocal, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lax.rsqrt, "cbrt": jnp.cbrt, "exp": jnp.exp, "log": jnp.log,
    "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "expm1": jnp.expm1, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfinv": lax.erf_inv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "sigmoid": jax.nn.sigmoid, "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}
for _n, _f in _UNARY.items():
    register(_n)(lambda x, _f=_f: _f(x))

_UNARY_NONDIFF = {
    "round": jnp.round, "rint": jnp.rint, "ceil": jnp.ceil,
    "floor": jnp.floor, "trunc": jnp.trunc, "fix": jnp.trunc,
    "isnan": lambda x: jnp.isnan(x).astype(_f32),
    "isinf": lambda x: jnp.isinf(x).astype(_f32),
    "isfinite": lambda x: jnp.isfinite(x).astype(_f32),
}
for _n, _f in _UNARY_NONDIFF.items():
    register(_n, differentiable=False)(lambda x, _f=_f: _f(x))

alias("negative", "_np_negative")
alias("log", "_np_log")


@register("cast")
def _cast(x, dtype=None):
    """reference: src/operator/tensor/elemwise_unary_op_basic.cc (Cast)."""
    return x.astype(np_dtype(dtype))


alias("cast", "Cast", "amp_cast")


@register("clip")
def _clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("identity")
def _identity(x):
    return x


alias("identity", "_copy", "stop_gradient_passthrough", "BlockGrad_inner")


@register("BlockGrad")
def _block_grad(x):
    """reference: src/operator/tensor/elemwise_unary_op_basic.cc (BlockGrad)."""
    return lax.stop_gradient(x)


alias("BlockGrad", "stop_gradient")


@register("zeros_like")
def _zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like")
def _ones_like(x):
    return jnp.ones_like(x)


@register("shape_array", differentiable=False)
def _shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register("size_array", differentiable=False)
def _size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int32)


# ---------------------------------------------------------------------------
# binary broadcast (reference: elemwise_binary_broadcast_op_basic.cc etc.)
# scalars are accepted directly, covering the reference's *_scalar variants.
# ---------------------------------------------------------------------------
_BINARY = {
    "broadcast_add": jnp.add, "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply, "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod, "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum, "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
for _n, _f in _BINARY.items():
    register(_n)(lambda a, b, _f=_f: _f(a, b))

alias("broadcast_add", "elemwise_add", "_plus", "_add", "_plus_scalar")
alias("broadcast_sub", "elemwise_sub", "_minus", "_sub", "_minus_scalar")
alias("broadcast_mul", "elemwise_mul", "_mul", "_mul_scalar")
alias("broadcast_div", "elemwise_div", "_div", "_div_scalar")
alias("broadcast_maximum", "maximum", "_maximum")
alias("broadcast_minimum", "minimum", "_minimum")
alias("broadcast_power", "_power", "_power_scalar", "pow")

_CMP = {
    "broadcast_equal": jnp.equal, "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less, "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}
for _n, _f in _CMP.items():
    register(_n, differentiable=False)(
        lambda a, b, _f=_f: _f(a, b).astype(
            a.dtype if hasattr(a, "dtype") and jnp.issubdtype(
                jnp.asarray(a).dtype, jnp.floating) else _f32))

alias("broadcast_equal", "_equal", "_equal_scalar")
alias("broadcast_not_equal", "_not_equal")
alias("broadcast_greater", "_greater", "_greater_scalar")
alias("broadcast_lesser", "_lesser", "_lesser_scalar")


@register("where")
def _where(condition, x, y):
    """reference: src/operator/tensor/control_flow_op.cc (where)."""
    return jnp.where(condition.astype(bool) if hasattr(condition, "astype")
                     else condition, x, y)


@register("smooth_l1")
def _smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


# ---------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------
def _reduce(fn):
    def impl(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, exclude, x.ndim)
        acc = _safe_acc_dtype(x)
        if acc is not None and fn in (jnp.sum, jnp.mean, jnp.prod):
            return fn(x.astype(acc), axis=ax, keepdims=keepdims).astype(x.dtype)
        return fn(x, axis=ax, keepdims=keepdims)
    return impl


register("sum")(_reduce(jnp.sum))
register("mean")(_reduce(jnp.mean))
register("prod")(_reduce(jnp.prod))
register("max")(_reduce(jnp.max))
register("min")(_reduce(jnp.min))
alias("sum", "sum_axis")
# _np_sum/_np_mean are NOT aliased to the legacy reduce ops: the numpy
# namespace registers them over jnp directly (dtype=, tuple-axis, numpy
# promotion), see mxnet_tpu/numpy/__init__.py
alias("max", "max_axis")
alias("min", "min_axis")


@register("nansum")
def _nansum(x, axis=None, keepdims=False, exclude=False):
    return jnp.nansum(x, axis=_norm_axis(axis, exclude, x.ndim), keepdims=keepdims)


@register("nanprod")
def _nanprod(x, axis=None, keepdims=False, exclude=False):
    return jnp.nanprod(x, axis=_norm_axis(axis, exclude, x.ndim), keepdims=keepdims)


@register("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    ax = axis if axis is None or isinstance(axis, tuple) else (axis,)
    acc = _safe_acc_dtype(x)
    xa = x.astype(acc) if acc is not None else x
    if ord == 1:
        r = jnp.sum(jnp.abs(xa), axis=ax, keepdims=keepdims)
    else:
        r = jnp.sqrt(jnp.sum(jnp.square(xa), axis=ax, keepdims=keepdims))
    return r.astype(x.dtype)


def _index_float():
    """Float dtype for mxnet's float-index convention. float32 is exact
    only to 2^24; inside mx.util.large_tensor_scope() positions can
    exceed 2^31, so the wide scope reports float64 (exact to 2^53)."""
    from ..base import x64_enabled
    return jnp.float64 if x64_enabled() else _f32


@register("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims=False):
    r = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return r.astype(_index_float())  # reference returns float indices


@register("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(
        _index_float())


@register("argsort", differentiable=False)
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    idx = jnp.argsort(x if is_ascend else -x, axis=axis, stable=True)
    return idx.astype(np_dtype(dtype))


@register("sort")
def _sort(x, axis=-1, is_ascend=True):
    s = jnp.sort(x, axis=axis)
    return s if is_ascend else jnp.flip(s, axis=axis)


@register("topk", differentiable=False)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """reference: src/operator/tensor/ordering_op.cc (topk)."""
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(np_dtype(dtype))
    return idx.astype(np_dtype(dtype))


@register("cumsum")
def _cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    r = jnp.cumsum(x, axis=axis)
    return r.astype(np_dtype(dtype)) if dtype is not None else r


# ---------------------------------------------------------------------------
# shape manipulation (reference: matrix_op.cc)
# ---------------------------------------------------------------------------
@register("reshape")
def _reshape(x, shape=None, reverse=False):
    """reference: matrix_op.cc (Reshape) — supports the 0/-1/-2/-3/-4 codes."""
    shape = tuple(shape)
    if any(s in (0, -2, -3, -4) for s in shape):
        shape = _mx_reshape(x.shape, shape, reverse)
    return jnp.reshape(x, shape)


def _mx_reshape(ishape, target, reverse=False):
    """Implement MXNet's special reshape codes:
    0 copy dim, -1 infer, -2 copy rest, -3 merge two, -4 split."""
    ishape = list(ishape[::-1]) if reverse else list(ishape)
    tgt = list(target[::-1]) if reverse else list(target)
    out = []
    i = 0
    j = 0
    while j < len(tgt):
        t = tgt[j]
        if t == 0:
            out.append(ishape[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1
        elif t == -2:
            out.extend(ishape[i:]); i = len(ishape)
        elif t == -3:
            out.append(ishape[i] * ishape[i + 1]); i += 2
        elif t == -4:
            d1, d2 = tgt[j + 1], tgt[j + 2]
            if d1 == -1:
                d1 = ishape[i] // d2
            if d2 == -1:
                d2 = ishape[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(t); i += 1
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in ishape:
            total *= d
        out[out.index(-1)] = total // known
    return tuple(out[::-1]) if reverse else tuple(out)


alias("reshape", "Reshape")


@register("flatten")
def _flatten(x):
    """reference: matrix_op.cc (Flatten) — keeps dim0, flattens the rest."""
    return jnp.reshape(x, (x.shape[0], -1))


alias("flatten", "Flatten")


@register("transpose")
def _transpose(x, axes=None):
    if not axes:
        axes = None
    return jnp.transpose(x, axes)


@register("expand_dims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@register("swapaxes")
def _swapaxes(x, dim1=0, dim2=1):
    return jnp.swapaxes(x, dim1, dim2)


alias("swapaxes", "SwapAxis")


@register("moveaxis")
def _moveaxis(x, source=0, destination=0):
    return jnp.moveaxis(x, source, destination)


@register("broadcast_to")
def _broadcast_to(x, shape=None):
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register("broadcast_axis")
def _broadcast_axis(x, axis=None, size=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("tile")
def _tile(x, reps=None):
    return jnp.tile(x, reps)


@register("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("pad")
def _pad(x, mode="constant", pad_width=None, constant_value=0.0):
    """reference: src/operator/pad.cc — pad_width in flattened begin/end pairs."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(x.ndim)]
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pw, mode="edge" if mode == "edge" else "reflect")


alias("pad", "Pad")


@register("slice")
def _slice(x, begin=None, end=None, step=None):
    """reference: matrix_op.cc (slice)."""
    nd = x.ndim
    begin = list(begin) + [None] * (nd - len(begin))
    end = list(end) + [None] * (nd - len(end))
    step = (list(step) + [None] * (nd - len(step))) if step else [None] * nd
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return x[idx]


@register("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def _slice_like(x, shape_like, axes=None):
    tgt = list(x.shape)
    axes = axes if axes else range(x.ndim)
    for a in axes:
        tgt[a] = shape_like.shape[a]
    return x[tuple(slice(0, t) for t in tgt)]


@register("reverse")
def _reverse(x, axis=0):
    return jnp.flip(x, axis=axis)


alias("reverse", "flip")


@register("concat")
def _concat(*xs, dim=1, num_args=None):
    """reference: src/operator/nn/concat.cc."""
    return jnp.concatenate(xs, axis=dim)


alias("concat", "Concat")


@register("stack")
def _stack(*xs, axis=0, num_args=None):
    return jnp.stack(xs, axis=axis)


@register("split", num_outputs=0)
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    """reference: src/operator/slice_channel.cc (SliceChannel)."""
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if num_outputs > 1 else parts[0]


alias("split", "SliceChannel")


@register("_split_v2", num_outputs=0)
def _split_v2_op(x, indices_or_sections=1, axis=0, squeeze_axis=False):
    """reference: matrix_op.cc (_split_v2) — split by count or indices."""
    parts = jnp.split(x, indices_or_sections, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("space_to_depth")
def _space_to_depth(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("depth_to_space")
def _depth_to_space(x, block_size=1):
    n, c, h, w = x.shape
    b = block_size
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


# ---------------------------------------------------------------------------
# dot / linalg (reference: src/operator/tensor/dot.cc, la_op.cc)
# ---------------------------------------------------------------------------
@register("dot")
def _dot(a, b, transpose_a=False, transpose_b=False):
    """reference: dot.cc — contracts last axis of a with first of b.
    On TPU this is the MXU path; keep operands large and let XLA tile."""
    if transpose_a:
        a = jnp.transpose(a)
    if transpose_b:
        b = jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    """reference: dot.cc (batch_dot) — leading dims are batch."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def _linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_syrk")
def _linalg_syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("khatri_rao")
def _khatri_rao(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


# ---------------------------------------------------------------------------
# indexing (reference: src/operator/tensor/indexing_op.cc)
# ---------------------------------------------------------------------------

def _as_index(i):
    """Index normalization: float indices (the mxnet convention) cast to
    the platform index width — int64 inside mx.util.large_tensor_scope()
    (x64 on), int32 otherwise. Integer inputs keep their width so int64
    indices survive for >2^31-element gathers."""
    from ..base import x64_enabled
    i = jnp.asarray(i)
    if jnp.issubdtype(i.dtype, jnp.integer):
        return i
    return i.astype(jnp.int64 if x64_enabled() else jnp.int32)

@register("take")
def _take(a, indices, axis=0, mode="clip"):
    idx = _as_index(indices)
    return jnp.take(a, idx, axis=axis,
                    mode="clip" if mode == "clip" else "wrap")


@register("pick")
def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(_as_index(index), 0, x.shape[axis] - 1)
    r = jnp.take_along_axis(x, jnp.expand_dims(idx, axis=axis), axis=axis)
    return r if keepdims else jnp.squeeze(r, axis=axis)


@register("gather_nd")
def _gather_nd(data, indices):
    """reference: indexing_op.cc (gather_nd) — indices shape (M, ...)."""
    idx = tuple(_as_index(indices))
    return data[idx]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=None):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(_as_index(indices))
    return out.at[idx].set(data)


@register("one_hot")
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=np_dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("Embedding")
def _embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
               sparse_grad=False):
    """reference: indexing_op.cc (Embedding). On TPU an embedding lookup is a
    gather; sparse_grad records a row-sparse cotangent (recorder below)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@_get_op("Embedding").recorder
def _embedding_recorder(raw_args, kwargs, nd_inputs, fn):
    """sparse_grad=True: the weight-gradient is recorded as (indices, values)
    rows and never densified on its way to the leaf (reference:
    indexing_op.cc EmbeddingOpBackward rowsparse kernel; the grad NDArray the
    user sees is kRowSparseStorage). Falls back to the generic dense vjp when
    tracing (hybridize), when the weight is itself an op output, or when
    data/weight are not plain NDArray inputs at positions 0/1."""
    if not kwargs.get("sparse_grad"):
        return None
    if len(raw_args) < 2 or len(nd_inputs) != 2:
        return None
    data, weight = raw_args[0], raw_args[1]
    # both inputs must be the NDArrays at positions 0/1 (a numpy `data`
    # arg shifts nd_inputs and the tape would mis-route cotangents)
    if nd_inputs[0]._read() is not data or nd_inputs[1]._read() is not weight:
        return None
    if isinstance(data, jax.core.Tracer) or isinstance(weight, jax.core.Tracer):
        return None
    if nd_inputs[1]._autograd_node is not None:
        return None

    def primal(d, w):
        # the resolved forward (tpu_impl / AMP applied) — never bypass it
        return fn(d, w, **kwargs)

    out = primal(data, weight)
    # jnp.take wraps negative lookups python-style and DROPS the cotangent
    # of still-out-of-range ones; the sparse rows must mirror both or the
    # grad diverges from the dense path (and the in-bounds invariant
    # downstream scatters rely on breaks)
    rows = weight.shape[0]
    raw_idx = data.astype(jnp.int32).reshape(-1)
    raw_idx = jnp.where(raw_idx < 0, raw_idx + rows, raw_idx)
    valid = (raw_idx >= 0) & (raw_idx < rows)
    flat_idx = jnp.clip(raw_idx, 0, rows - 1)
    row_shape = weight.shape[1:]
    w_shape = weight.shape
    vmask = valid.reshape((-1,) + (1,) * len(row_shape))

    def vjp_fn(cot):
        from .. import autograd as _ag
        vals = cot.reshape((-1,) + row_shape).astype(weight.dtype)
        return (None, _ag.RowSparseRows(flat_idx, jnp.where(vmask, vals, 0),
                                        w_shape))

    return out, vjp_fn, primal


@register("_copyto")
def _copyto_op(data):
    """Identity copy with gradient (reference: _copyto — NDArray.copy/
    copyto are recorded ops there; a raw buffer copy would silently
    detach the tape, the same failure class as unrecorded slicing).
    Sharing the immutable buffer IS the copy semantics here (same as the
    non-recording copy); `data + 0` would promote bool to int32."""
    return data


@register("_internal_getitem")
def _internal_getitem(data, index=None):
    """Tape-recorded `x[key]` (reference: slicing is the `slice`/`gather`
    op family with FGradient there; a raw view would silently detach the
    autograd graph). `index` is the python indexing key, closed over —
    its vjp scatters the cotangent back into the sliced positions."""
    return data[index]


@register("take_along_axis")
def _take_along_axis(a, indices, axis=0):
    return jnp.take_along_axis(a, _as_index(indices), axis=axis)


@register("where_index", differentiable=False)
def _where_index(x):
    # dynamic-shape op: only usable eagerly (documented XLA constraint)
    return jnp.asarray(_np.nonzero(_np.asarray(x))[0], dtype=jnp.int32)


@register("boolean_mask", differentiable=False)
def _boolean_mask(data, index, axis=0):
    mask = _np.asarray(index).astype(bool)
    return jnp.compress(mask, data, axis=axis)


@register("SequenceMask")
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0):
    """reference: src/operator/sequence_mask.cc — mask time axis by length."""
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        mask = steps[None, :] < sequence_length[:, None].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


@register("SequenceLast")
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = -1 if axis == 0 else -1
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return jnp.take_along_axis(
            data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
        ).squeeze(0)
    return jnp.take_along_axis(
        data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1).squeeze(1)


@register("SequenceReverse")
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < L, L - 1 - steps, steps)
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
@register("diag")
def _diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("eye", creation=True)
def _eye(N=1, M=0, k=0, ctx=None, dtype="float32"):
    return jnp.eye(int(N), int(M) if M else None, k=int(k), dtype=np_dtype(dtype))


@register("logsumexp")
def _logsumexp(x, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


# ---------------------------------------------------------------------------
# sparse kernels (reference: src/operator/tensor/dot.cc csr FComputeEx).
# Raw-array ops so the autograd tape records them: cotangents flow to the
# dense rhs (and to sp_data) through gather/segment_sum transposes — the
# backward the reference hand-writes in dot_backward_csr.
# ---------------------------------------------------------------------------
@register("_sparse_dot_csr_dense", arity=4)
def _sparse_dot_csr_dense(sp_data, sp_indices, rows, rhs, m=0, k=0,
                          transpose_a=False):
    """csr(m,k) · dense(k,n) (or csrᵀ · dense → (k,n)): per-nnz gather +
    segment-sum, the TPU-friendly formulation (MXU-free but fuses well)."""
    rows = rows.astype(jnp.int32)
    cols = sp_indices.astype(jnp.int32)
    if transpose_a:
        contrib = sp_data[:, None] * rhs[rows]
        out = jnp.zeros((int(k), rhs.shape[1]), dtype=contrib.dtype)
        return out.at[cols].add(contrib)
    gathered = rhs[cols]
    contrib = sp_data[:, None] * gathered
    return jax.ops.segment_sum(contrib, rows, num_segments=int(m))
