"""The operator registry.

TPU-native analog of the reference's NNVM op registry (reference:
3rdparty/tvm/nnvm/include/nnvm/op.h (NNVM_REGISTER_OP), src/operator/*
(FCompute<xpu> attrs)). One registration per op; the `mx.nd` and `mx.sym`
namespaces are both code-generated from this table (reference:
python/mxnet/ndarray/register.py, python/mxnet/symbol/register.py), so an op
defined once is available imperatively, symbolically, and inside `hybridize()`
traces.

An op's `fn` operates on raw jax arrays (or tracers) and returns an array or a
tuple of arrays. Device dispatch (the reference's FCompute<cpu>/FCompute<gpu>/
FCompute<tpu> split) collapses to XLA: the same jax fn lowers to every
platform, with optional per-op Pallas overrides for TPU registered via
`tpu_impl` (the FCompute<tpu> hook of the north star).
"""
from __future__ import annotations

import functools

__all__ = ["Operator", "register", "get", "list_ops", "alias"]

_REGISTRY: dict = {}


class Operator:
    def __init__(self, name, fn, *, arity=None, differentiable=True,
                 creation=False, random=False, num_outputs=1, doc=None):
        self.name = name
        self.fn = fn
        self.arity = arity            # number of array inputs; None = variadic
        self.differentiable = differentiable
        self.creation = creation      # takes no array inputs (zeros, uniform, ...)
        self.random = random          # consumes an RNG key kwarg
        self.num_outputs = num_outputs
        self.doc = doc or (fn.__doc__ if fn else None)
        self.tpu_fn = None            # optional Pallas/TPU-specialized impl
        self.shape_hint = None        # fn(in_shapes, kwargs) -> in_shapes
        #   fills unknown (None) input shapes from known ones — the forward
        #   half of the reference's bidirectional FInferShape
        self.vjp_rule = None          # optional FGradient-style rule
        self.record_override = None   # optional custom tape recording:
        #   f(raw_args, kwargs, nd_inputs, fn) -> (out_raw, vjp_fn,
        #   primal_fn) or None to fall back to the generic jax.vjp path.
        #   `fn` is the already-resolved forward (tpu_impl/AMP applied) —
        #   overrides must compute the output through it so specialization
        #   is never bypassed. The hook for ops whose gradient has
        #   non-dense structure (the FGradient-with-FInferStorageType
        #   analog: Embedding sparse_grad -> rowsparse).

    def tpu_impl(self, fn):
        """Register a TPU-specialized (Pallas) implementation.
        The FCompute<tpu> hook of the north star (BASELINE.json)."""
        self.tpu_fn = fn
        return fn

    def recorder(self, fn):
        """Register a custom tape-recording path (see record_override)."""
        self.record_override = fn
        return fn

    def def_grad(self, fn):
        """Register a hand-written vjp rule — the FGradient analog
        (reference: NNVM_REGISTER_OP(...).set_attr<FGradient>(...)).

        fn(cot, out_raw, raw_args, kwargs, nd_positions) -> tuple of
        cotangents aligned with nd_positions (None where undefined).
        With a rule, the eager tape records WITHOUT calling jax.vjp —
        the per-op trace (~2 ms) collapses to a plain forward, and the
        backward runs the rule's jnp math directly."""
        self.vjp_rule = fn
        return fn

    def best_fn(self, on_tpu):
        if on_tpu and self.tpu_fn is not None:
            from ..base import get_env
            if get_env("MXNET_TPU_USE_PALLAS"):
                return self.tpu_fn
        return self.fn

    def __repr__(self):
        return "Operator(%s)" % self.name


def register(name, **meta):
    """Decorator: register a jax-level op implementation under `name`.

    reference: NNVM_REGISTER_OP(name).set_attr<FCompute>(...)
    """
    def deco(fn):
        if name in _REGISTRY:
            raise ValueError("op %s already registered" % name)
        _REGISTRY[name] = Operator(name, fn, **meta)
        return fn
    return deco


def alias(existing, *names):
    """Register additional names for an op (reference: .add_alias)."""
    op = _REGISTRY[existing]
    for n in names:
        _REGISTRY[n] = op


def get(name):
    return _REGISTRY[name]


def list_ops():
    return sorted(_REGISTRY)


def defun(name, **meta):
    """Register and return a plain callable (for internal reuse)."""
    def deco(fn):
        register(name, **meta)(fn)
        return fn
    return deco
