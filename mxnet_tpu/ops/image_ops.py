"""The `_image_*` op family → `mx.nd.image` / `mx.sym.image`.

reference: src/operator/image/image_random-inl.h (ToTensor, Normalize,
flips, random flips), resize-inl.h (Resize), crop-inl.h (Crop), exposed
in python as mx.nd.image.* / mx.sym.image.*. On TPU resize lowers to
jax.image.resize (XLA gather/dot programs); everything else is
layout/elementwise work XLA fuses.

Layout contract (same as the reference): images are HWC or NHWC for
to_tensor/resize/crop/flips; to_tensor emits CHW/NCHW float32 in [0, 1];
normalize consumes the CHW/NCHW tensor form.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _saturate_cast(out, dtype):
    """Round+clip back into an integer input dtype (the reference's
    cv::saturate_cast); float dtypes pass through astype."""
    import numpy as _np
    if _np.issubdtype(_np.dtype(dtype), _np.integer):
        info = _np.iinfo(_np.dtype(dtype))
        return jnp.clip(jnp.round(out), info.min, info.max).astype(dtype)
    return out.astype(dtype)


@register("_image_to_tensor")
def _to_tensor(data):
    """HWC [0,255] uint8/float → CHW float32 [0,1] (reference: ToTensor)."""
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize")
def _normalize(data, mean=0.0, std=1.0):
    """(x - mean) / std over the channel axis of CHW/NCHW float input
    (reference: Normalize)."""
    mean = jnp.asarray(mean, dtype=data.dtype).reshape(-1)
    std = jnp.asarray(std, dtype=data.dtype).reshape(-1)
    ax = data.ndim - 3  # channel axis: 0 for CHW, 1 for NCHW
    shape = [1] * data.ndim
    shape[ax] = -1
    return (data - mean.reshape(shape)) / std.reshape(shape)


def _resize_hw(img, size, keep_ratio, interp):
    h, w = img.shape[-3], img.shape[-2]
    if isinstance(size, int):
        if keep_ratio:
            if h > w:
                new_w, new_h = size, int(h * size / w)
            else:
                new_w, new_h = int(w * size / h), size
        else:
            new_w = new_h = size
    else:
        new_w, new_h = size
    method = "nearest" if interp == 0 else "linear"
    out_shape = img.shape[:-3] + (new_h, new_w, img.shape[-1])
    out = jax.image.resize(img.astype(jnp.float32), out_shape, method=method)
    return _saturate_cast(out, img.dtype)


@register("_image_resize")
def _resize(data, size=0, keep_ratio=False, interp=1):
    """Resize HWC/NHWC (reference: Resize; size int or (w, h))."""
    size = tuple(size) if isinstance(size, (tuple, list)) else int(size)
    return _resize_hw(data, size, keep_ratio, interp)


@register("_image_crop")
def _crop(data, x=0, y=0, width=0, height=0):
    """Spatial crop of HWC/NHWC (reference: Crop(x, y, width, height))."""
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@register("_image_flip_left_right")
def _flip_lr(data):
    return jnp.flip(data, axis=data.ndim - 2)


@register("_image_flip_top_bottom")
def _flip_tb(data):
    return jnp.flip(data, axis=data.ndim - 3)


@register("_image_random_flip_left_right", random=True)
def _random_flip_lr(data, key=None):
    flip = jax.random.bernoulli(key)
    return jnp.where(flip, jnp.flip(data, axis=data.ndim - 2), data)


@register("_image_random_flip_top_bottom", random=True)
def _random_flip_tb(data, key=None):
    flip = jax.random.bernoulli(key)
    return jnp.where(flip, jnp.flip(data, axis=data.ndim - 3), data)


@register("_image_random_brightness", random=True)
def _random_brightness(data, min_factor=1.0, max_factor=1.0, key=None):
    f = jax.random.uniform(key, minval=min_factor, maxval=max_factor)
    return _saturate_cast(data.astype(jnp.float32) * f, data.dtype)


@register("_image_random_contrast", random=True)
def _random_contrast(data, min_factor=1.0, max_factor=1.0, key=None):
    f = jax.random.uniform(key, minval=min_factor, maxval=max_factor)
    # grayscale mean over the trailing HWC dims (reference coefficients)
    coef = jnp.asarray([0.299, 0.587, 0.114], dtype=jnp.float32)
    gray = (data.astype(jnp.float32) * coef).sum(axis=-1, keepdims=True)
    mean = gray.mean(axis=(-3, -2), keepdims=True)
    return _saturate_cast(data.astype(jnp.float32) * f + mean * (1 - f),
                          data.dtype)
