"""Operator library: the registry plus all op-definition modules.

Importing this package registers every op (reference: static registration of
NNVM_REGISTER_OP at libmxnet.so load time).
"""
from . import registry
from .registry import register, alias, get, list_ops

from . import tensor      # noqa: F401  elementwise/broadcast/reduce/shape
from . import nn          # noqa: F401  FC/conv/pool/norm/softmax/dropout
from . import random_ops  # noqa: F401  sampling ops
from . import optimizer_ops  # noqa: F401  sgd/adam/... update kernels
from . import rnn_ops      # noqa: F401  fused RNN/LSTM/GRU via lax.scan
from . import quantization_ops  # noqa: F401  int8 quantize/dequant/QFC/QConv
from . import extended     # noqa: F401  linalg_* / multi_* / LRN / SVM / ST
from . import contrib_vision  # noqa: F401  box_nms/ROIAlign/resize/adaptive
from . import image_ops    # noqa: F401  _image_* family (nd.image/sym.image)
from . import grad_rules   # noqa: F401  FGradient-style vjp rules (hot ops)
from . import fused_conv   # noqa: F401  Pallas conv+BN+ReLU fusion
from . import fused_optimizer  # noqa: F401  Pallas fused optimizer kernels
from . import sparse_ops   # noqa: F401  Pallas sparse segment-sum scatter-add
from . import shape_hints  # noqa: F401  FInferShape-style param-shape hints
