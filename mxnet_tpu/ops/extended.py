"""Op-parity closure: the reference user-facing ops that had no entry yet.

reference: src/operator/tensor/la_op.cc (linalg_*), optimizer_op.cc
(multi_* fused multi-tensor updates, mp_* mixed-precision variants),
src/operator/{lrn.cc, svm_output.cc, spatial_transformer.cc,
identity_attach_KL_sparse_reg.cc}, matrix_op.cc (batch_take,
fill_element_0index, unravel_index, reshape_like), broadcast ops.

Multi-tensor optimizer ops take interleaved variadic inputs exactly like
the reference (weights/grads[/moms][/w32s] flattened into one input list)
— one registry op per variant so Optimizer's aggregated update path and
the reference's call signatures line up.
"""
from __future__ import annotations

import functools

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias, get

# ---------------------------------------------------------------------------
# simple elementwise / shape ops
# ---------------------------------------------------------------------------


@register("rcbrt")
def _rcbrt(data):
    return 1.0 / jnp.cbrt(data)


@register("add_n", arity=None)
def _add_n(*args, num_args=None):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


alias("add_n", "ElementWiseSum")


@register("reshape_like", arity=2)
def _reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                  rhs_end=None):
    if lhs_begin is None and rhs_begin is None:
        return lhs.reshape(rhs.shape)
    lb = 0 if lhs_begin is None else int(lhs_begin)
    le = lhs.ndim if lhs_end is None else int(lhs_end)
    rb = 0 if rhs_begin is None else int(rhs_begin)
    re_ = rhs.ndim if rhs_end is None else int(rhs_end)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return lhs.reshape(new_shape)


@register("unravel_index", differentiable=False)
def _unravel_index(data, shape=None):
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    idx = jnp.unravel_index(data.astype(dt), tuple(shape))
    return jnp.stack(idx, axis=0).astype(data.dtype)


@register("argmax_channel", differentiable=False)
def _argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("batch_take", arity=2)
def _batch_take(a, indices):
    """out[i] = a[i, indices[i]] (reference: matrix_op.cc batch_take)."""
    from .tensor import _as_index
    return jnp.take_along_axis(
        a, _as_index(indices)[..., None], axis=1)[..., 0]


@register("fill_element_0index", arity=3, differentiable=False)
def _fill_element_0index(lhs, mhs, rhs):
    """out = lhs; out[i, mhs[i]] = rhs[i] (legacy assign op)."""
    rows = jnp.arange(lhs.shape[0])
    return lhs.at[rows, mhs.astype(jnp.int32)].set(rhs)


@register("moments")
def _moments(data, axes=None, keepdims=False):
    axes = tuple(axes) if axes is not None else None
    return (jnp.mean(data, axis=axes, keepdims=keepdims),
            jnp.var(data, axis=axes, keepdims=keepdims))


_moments_op = get("moments")
_moments_op.num_outputs = 2


@register("make_loss")
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    # same semantics as the capitalized op (nn.py MakeLoss), incl. the
    # grad_scale/normalization backward
    return get("MakeLoss").fn(data, grad_scale=grad_scale,
                              valid_thresh=valid_thresh,
                              normalization=normalization)


@register("cast_storage", differentiable=False)
def _cast_storage(data, stype=None):
    # dense payloads are identity; the sparse wrapper layer
    # (ndarray/sparse.py tostype) owns real storage conversion
    return data


def _kl_sparse_reg_make():
    """reference: identity_attach_KL_sparse_reg.cc — identity forward;
    the backward ADDS the KL(rho || rho_hat) sparsity-penalty gradient
    (rho_hat = batch-mean activation per unit) to the incoming cotangent.
    Like the SoftmaxOutput family, this backward is deliberately NOT the
    vjp of the forward. The reference's momentum running average of
    rho_hat is an engine aux state; the pure-op form uses the batch mean
    (momentum accepted for API parity)."""
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
    def reg(data, target, penalty):
        return data

    def fwd(data, target, penalty):
        rho_hat = jnp.clip(jnp.mean(data.astype(jnp.float32), axis=0),
                           1e-6, 1.0 - 1e-6)
        return data, rho_hat

    def bwd(target, penalty, rho_hat, g):
        # batch size and dtype come off the cotangent (same shape/dtype
        # as the identity output)
        dkl = -target / rho_hat + (1.0 - target) / (1.0 - rho_hat)
        grad = g.astype(jnp.float32) + penalty * dkl[None] / g.shape[0]
        return (grad.astype(g.dtype),)

    reg.defvjp(fwd, bwd)
    return reg


_kl_reg_core = _kl_sparse_reg_make()


@register("IdentityAttachKLSparseReg")
def _identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                   penalty=0.001, momentum=0.9):
    """Identity forward; backward attaches the KL sparsity-penalty
    gradient (see _kl_sparse_reg_make)."""
    return _kl_reg_core(data, float(sparseness_target), float(penalty))


# ---------------------------------------------------------------------------
# broadcast aliases
# ---------------------------------------------------------------------------
@register("broadcast_like", arity=2)
def _broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[int(la)] = rhs.shape[int(ra)]
    return jnp.broadcast_to(lhs, tuple(shape))


def _register_broadcast_axes():
    if "broadcast_axis" in __import__(
            "mxnet_tpu.ops.registry", fromlist=["_REGISTRY"])._REGISTRY:
        alias("broadcast_axis", "broadcast_axes")
    else:
        @register("broadcast_axes")
        def _broadcast_axes(data, axis=None, size=None):
            axis = (axis,) if isinstance(axis, int) else tuple(axis)
            size = (size,) if isinstance(size, int) else tuple(size)
            shape = list(data.shape)
            for a, s in zip(axis, size):
                shape[a] = s
            return jnp.broadcast_to(data, tuple(shape))


_register_broadcast_axes()


# ---------------------------------------------------------------------------
# LRN / SVMOutput / SpatialTransformer / BatchNorm_v1
# ---------------------------------------------------------------------------
@register("LRN")
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization across channels (reference: lrn.cc):
    out = x / (knorm + alpha/nsize * sum_window(x^2))^beta."""
    sq = jnp.square(data.astype(jnp.float32))
    half = int(nsize) // 2
    # sum over a channel window via padded cumulative trick
    pad = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    window = sum(pad[:, i:i + data.shape[1]] for i in range(int(nsize)))
    norm = (knorm + (alpha / nsize) * window) ** beta
    return (data.astype(jnp.float32) / norm).astype(data.dtype)


def _svm_output_make():
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def svm(data, label, margin, regularization_coefficient, use_linear):
        return data

    def fwd(data, label, margin, reg, use_linear):
        return data, (data, label)

    def bwd(margin, reg, use_linear, res, g):
        data, label = res
        n, k = data.shape[0], data.shape[1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), k,
                                dtype=data.dtype)
        # hinge: for wrong classes with score > correct - margin, push down;
        # correct class pushed up by the number of violators
        correct = jnp.sum(data * onehot, axis=1, keepdims=True)
        viol = ((data - correct + margin) > 0) & (onehot == 0)
        violf = viol.astype(data.dtype)
        if use_linear:
            grad = violf - onehot * jnp.sum(violf, axis=1, keepdims=True)
        else:  # squared hinge
            m = jnp.maximum(data - correct + margin, 0) * (1 - onehot)
            grad = 2 * m - onehot * jnp.sum(2 * m, axis=1, keepdims=True)
        return (reg * grad * g, jnp.zeros_like(label))

    svm.defvjp(fwd, bwd)
    return svm


_svm_core = _svm_output_make()


@register("SVMOutput")
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """reference: svm_output.cc — identity forward, hinge-loss backward."""
    return _svm_core(data, label, float(margin),
                     float(regularization_coefficient), bool(use_linear))


@register("SpatialTransformer", arity=2)
def _spatial_transformer(data, loc, target_shape=None,
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=None):
    """reference: spatial_transformer.cc — affine grid + bilinear sampling,
    composed from the registered GridGenerator/BilinearSampler ops."""
    grid = get("GridGenerator").fn(loc, transform_type=transform_type,
                                   target_shape=target_shape)
    return get("BilinearSampler").fn(data, grid)


def _register_bn_v1():
    alias("BatchNorm", "BatchNorm_v1")


_register_bn_v1()


# ---------------------------------------------------------------------------
# linalg_* (reference: la_op.cc) — jnp.linalg on the MXU where applicable
# ---------------------------------------------------------------------------
@register("linalg_det")
def _linalg_det(a):
    return jnp.linalg.det(a)


@register("linalg_slogdet")
def _linalg_slogdet(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


get("linalg_slogdet").num_outputs = 2


@register("linalg_inverse")
def _linalg_inverse(a):
    return jnp.linalg.inv(a)


@register("linalg_potri")
def _linalg_potri(a):
    """Inverse from a Cholesky factor: inv(L L^T) (reference: la_op.cc)."""
    eye = jnp.eye(a.shape[-1], dtype=a.dtype)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_gemm", arity=3)
def _linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    if axis != -2:
        raise NotImplementedError("linalg_gemm: only axis=-2 (got %r)" % axis)
    ta = jnp.swapaxes(a, -1, -2) if transpose_a else a
    tb = jnp.swapaxes(b, -1, -2) if transpose_b else b
    return alpha * jnp.matmul(ta, tb) + beta * c


@register("linalg_trmm", arity=2)
def _linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    t = jnp.swapaxes(a, -1, -2) if transpose else a
    return alpha * (jnp.matmul(b, t) if rightside else jnp.matmul(t, b))


@register("linalg_trsm", arity=2)
def _linalg_trsm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    import jax.scipy.linalg as jsl
    if rightside:
        # solve X A = alpha B  ->  A^T X^T = alpha B^T
        xt = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                  jnp.swapaxes(alpha * b, -1, -2),
                                  lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(xt, -1, -2)
    return jsl.solve_triangular(a, alpha * b, lower=lower,
                                trans=1 if transpose else 0)


@register("linalg_gelqf")
def _linalg_gelqf(a):
    """LQ factorization: A = L Q (reference: la_op.cc gelqf) via QR of A^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


get("linalg_gelqf").num_outputs = 2


@register("linalg_makediag")
def _linalg_makediag(a, offset=0):
    return jnp.vectorize(lambda v: jnp.diag(v, k=int(offset)),
                         signature="(n)->(m,m)")(a)


@register("linalg_extractdiag")
def _linalg_extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=int(offset), axis1=-2, axis2=-1)


@register("linalg_sumlogdiag")
def _linalg_sumlogdiag(a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register("linalg_maketrian")
def _linalg_maketrian(a, offset=0, lower=True):
    """Pack a vector into a (lower) triangular matrix (la_op.cc)."""
    if offset != 0:
        raise NotImplementedError(
            "linalg_maketrian: only offset=0 (got %r)" % offset)
    n_elem = a.shape[-1]
    n = int((_np.sqrt(8 * n_elem + 1) - 1) / 2)
    idx = _np.tril_indices(n) if lower else _np.triu_indices(n)

    def pack(v):
        m = jnp.zeros((n, n), a.dtype)
        return m.at[idx].set(v)

    return jnp.vectorize(pack, signature="(k)->(n,n)")(a)


@register("linalg_extracttrian")
def _linalg_extracttrian(a, offset=0, lower=True):
    if offset != 0:
        raise NotImplementedError(
            "linalg_extracttrian: only offset=0 (got %r)" % offset)
    n = a.shape[-1]
    idx = _np.tril_indices(n) if lower else _np.triu_indices(n)

    def unpack(m):
        return m[idx]

    return jnp.vectorize(unpack, signature="(n,n)->(k)")(a)


# ---------------------------------------------------------------------------
# multi-tensor fused optimizer updates (reference: optimizer_op.cc
# multi_sgd_update etc. — one launch updating many params). Inputs are the
# reference's interleaved flat list.
# ---------------------------------------------------------------------------
def _chunk(args, n_per):
    k = len(args) // n_per
    return [args[i * n_per:(i + 1) * n_per] for i in range(k)]


def _scalar_list(v, k):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * k


def _multi_update(args, n_per, upd, lrs, wds, **kw):
    groups = _chunk(args, n_per)
    lrs = _scalar_list(lrs, len(groups))
    wds = _scalar_list(wds, len(groups))
    outs = []
    for g, lr, wd in zip(groups, lrs, wds):
        outs.extend(upd(g, lr, wd, **kw))
    return tuple(outs)


@register("multi_sgd_update", arity=None, differentiable=False,
          num_outputs=0)
def _multi_sgd_update(*args, lrs=None, wds=None, rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=None):
    def upd(g, lr, wd):
        w, grad = g
        return [get("sgd_update").fn(w, grad, lr=lr, wd=wd,
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)]
    return _multi_update(args, 2, upd, lrs, wds)


@register("multi_sgd_mom_update", arity=None, differentiable=False,
          num_outputs=0)
def _multi_sgd_mom_update(*args, lrs=None, wds=None, momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=None):
    def upd(g, lr, wd):
        w, grad, mom = g
        return list(get("sgd_mom_update").fn(
            w, grad, mom, lr=lr, wd=wd, momentum=momentum,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return _multi_update(args, 3, upd, lrs, wds)


@register("multi_mp_sgd_update", arity=None, differentiable=False,
          num_outputs=0)
def _multi_mp_sgd_update(*args, lrs=None, wds=None, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=None):
    def upd(g, lr, wd):
        w, grad, w32 = g
        return list(get("mp_sgd_update").fn(
            w, grad, w32, lr=lr, wd=wd, rescale_grad=rescale_grad,
            clip_gradient=clip_gradient))
    return _multi_update(args, 3, upd, lrs, wds)


@register("multi_mp_sgd_mom_update", arity=None, differentiable=False,
          num_outputs=0)
def _multi_mp_sgd_mom_update(*args, lrs=None, wds=None, momentum=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             num_weights=None):
    def upd(g, lr, wd):
        w, grad, mom, w32 = g
        return list(get("mp_sgd_mom_update").fn(
            w, grad, mom, w32, lr=lr, wd=wd, momentum=momentum,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient))
    return _multi_update(args, 4, upd, lrs, wds)


alias("multi_mp_sgd_mom_update", "preloaded_multi_mp_sgd_mom_update")


@register("multi_all_finite", arity=None, differentiable=False)
def _multi_all_finite(*args, num_arrays=None, init_output=True):
    ok = jnp.bool_(True) if init_output else None
    for a in args:
        fin = jnp.all(jnp.isfinite(a.astype(jnp.float32)))
        ok = fin if ok is None else jnp.logical_and(ok, fin)
    return ok.astype(jnp.float32).reshape(1)


@register("multi_lars", arity=None, differentiable=False)
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-8, rescale_grad=1.0):
    """reference: optimizer_op.cc multi_lars — layerwise LARS trust ratio."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * trust


def _lamb_phase1(weight, grad, mean, var, beta1, beta2, epsilon, t, wd,
                 rescale_grad, clip_grad, bias_correction):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * g * g
    mh, vh = m, v
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    upd = mh / (jnp.sqrt(vh) + epsilon) + wd * weight.astype(jnp.float32)
    return upd, m, v


@register("mp_lamb_update_phase1", arity=4, differentiable=False,
          num_outputs=3)
def _mp_lamb_update_phase1(weight, grad, mean, var, weight32=None, beta1=0.9,
                           beta2=0.999, epsilon=1e-6, t=1, wd=0.0,
                           rescale_grad=1.0, clip_gradient=-1.0,
                           bias_correction=True):
    w = weight32 if weight32 is not None else weight
    upd, m, v = _lamb_phase1(w, grad, mean, var, beta1, beta2, epsilon, t,
                             wd, rescale_grad, clip_gradient,
                             bias_correction)
    return upd, m, v


@register("mp_lamb_update_phase2", arity=4, differentiable=False,
          num_outputs=2)
def _mp_lamb_update_phase2(weight, g, r1, r2, weight32=None, lr=0.01,
                           lower_bound=-1.0, upper_bound=-1.0):
    w32 = (weight32 if weight32 is not None else weight).astype(jnp.float32)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    if lower_bound > 0:
        ratio = jnp.maximum(ratio, lower_bound)
    if upper_bound > 0:
        ratio = jnp.minimum(ratio, upper_bound)
    new32 = w32 - lr * ratio * g
    return new32.astype(weight.dtype), new32


@register("ravel_multi_index", differentiable=False)
def _ravel_multi_index(data, shape=None):
    """reference: src/operator/tensor/ravel.cc (_ravel_multi_index) —
    (ndim, N) coordinates → flat indices under `shape`. int64 only under
    x64 / large_tensor_scope; int32 otherwise (avoids jax's truncation
    warning on the default build)."""
    dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    coords = tuple(data.astype(dt))
    return jnp.ravel_multi_index(coords, tuple(shape), mode="clip") \
        .astype(dt)


@register("linspace", creation=True)
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, ctx=None,
              dtype="float32"):
    """reference: np-compat linspace op (mx.nd.linspace)."""
    from ..base import np_dtype
    return jnp.linspace(float(start), float(stop), int(num),
                        endpoint=bool(endpoint)).astype(np_dtype(dtype))


@register("digamma")
def _digamma(data):
    """reference: unary_op psi (mx.nd.digamma)."""
    return jax.scipy.special.digamma(data)


def _im2col_fn(data, kernel, stride, dilate, pad):
    n, c = data.shape[0], data.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        data.astype(jnp.float32), filter_shape=tuple(kernel),
        window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: (N, C*prod(kernel), out_h, out_w) -> (N, C*K, L)
    return patches.reshape(n, patches.shape[1], -1).astype(data.dtype)


def _conv_tuples(kernel, stride, dilate, pad):
    k = tuple(kernel)
    nd_ = len(k)
    def _t(v, d):
        if v is None:
            return (d,) * nd_
        v = tuple(v) if isinstance(v, (tuple, list)) else (v,) * nd_
        return v
    return k, _t(stride, 1), _t(dilate, 1), _t(pad, 0)


@register("im2col")
def _im2col(data, kernel=None, stride=None, dilate=None, pad=None):
    """reference: src/operator/nn/im2col.h (im2col op) — unfold sliding
    conv patches into a (N, C*prod(kernel), L) matrix."""
    k, s, d, p = _conv_tuples(kernel, stride, dilate, pad)
    return _im2col_fn(data, k, s, d, p)


@register("col2im")
def _col2im(data, output_size=None, kernel=None, stride=None, dilate=None,
            pad=None):
    """reference: im2col.h (col2im op) — exact transpose of im2col:
    scatter-add the column matrix back into (N, C, *output_size).
    Implemented as the vjp of im2col, which IS that transpose."""
    k, s, d, p = _conv_tuples(kernel, stride, dilate, pad)
    out_size = tuple(output_size)
    n = data.shape[0]
    c = data.shape[1] // _prod(k)
    zeros = jnp.zeros((n, c) + out_size, dtype=data.dtype)
    _, vjp = jax.vjp(lambda x: _im2col_fn(x, k, s, d, p), zeros)
    (img,) = vjp(data)
    return img


def _prod(xs):
    r = 1
    for x in xs:
        r *= x
    return r
