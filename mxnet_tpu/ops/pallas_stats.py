"""Pallas kernel-layer shared utilities: dispatch observability — the
counters/spans that make the kernel layer auditable (ISSUE 10 tentpole
part 3) — plus the version-tolerance shims every kernel module needs.

Every Pallas kernel call site in the ops layer reports through here:

* ``ops.pallas.dispatch`` (+ ``ops.pallas.dispatch.<kernel>``) counts each
  decision to run a Pallas kernel;
* ``ops.pallas.fallback`` (+ ``ops.pallas.fallback.<reason>``) counts each
  time the Pallas path was REQUESTED (gate on) but the shape/dtype gate sent
  the call to the XLA composite instead — fallbacks are counted, never
  errors, so an ineligible tensor silently gets the always-correct path;
* ``kernel_span(name)`` wraps a dispatch in a ``pallas.<name>`` telemetry
  span (cat ``kernel``) so chrome traces show which stages ran fused.

Counting context: eager call sites count once per call; sites inside a
``custom_vjp``/``jit`` trace (the fused conv backward under a compiled train
step) count once per (re)trace — dispatches-per-program, not per step, the
same convention as `engine.reassociate_bucketed`. ``parse_log --kernels``
renders the table.
"""
from __future__ import annotations

import contextlib
import time

__all__ = ["note_dispatch", "note_fallback", "kernel_span",
           "compiler_params"]


def compiler_params(semantics):
    """Version-tolerant Mosaic params: the class is `CompilerParams` on
    current jax and `TPUCompilerParams` on the 0.4.3x line (the bare
    AttributeError killed every interpret-mode kernel test on jaxlib
    0.4.36); None when neither accepts dimension_semantics. Shared by
    fused_conv, fused_optimizer, and parallel/flash_attention."""
    from jax.experimental.pallas import tpu as pltpu
    for name in ("CompilerParams", "TPUCompilerParams"):
        cls = getattr(pltpu, name, None)
        if cls is not None:
            try:
                return cls(dimension_semantics=semantics)
            except TypeError:
                return None
    return None


def note_dispatch(kernel):
    """Count one Pallas kernel dispatch (total + per-kernel)."""
    from .. import telemetry as _telem
    if _telem.ENABLED:
        _telem.inc("ops.pallas.dispatch")
        _telem.inc("ops.pallas.dispatch.%s" % kernel)


def note_fallback(kernel, reason):
    """Count one gated-but-ineligible call routed to the XLA composite."""
    from .. import telemetry as _telem
    if _telem.ENABLED:
        _telem.inc("ops.pallas.fallback")
        _telem.inc("ops.pallas.fallback.%s" % reason)
        _telem.inc("ops.pallas.fallback.%s.%s" % (kernel, reason))


@contextlib.contextmanager
def kernel_span(kernel):
    """`pallas.<kernel>` telemetry span around a dispatch. Measures host
    wall time of the dispatch (eager: launch + any sync the caller does
    inside; traced: trace time) — perf evidence comes from the bench, the
    span is for WHICH-stage-ran-fused attribution."""
    from .. import telemetry as _telem
    if not _telem.ENABLED:
        yield
        return
    ts = _telem.span_clock()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _telem.record_span("pallas.%s" % kernel, "kernel", ts,
                           time.perf_counter() - t0)
