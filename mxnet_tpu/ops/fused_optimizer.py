"""Pallas fused optimizer-update kernels (ISSUE 10 tentpole part 2).

The "Tensor Processing Primitives" blueprint (PAPERS.md) applied to the
weight update: ONE streaming pass over param/grad/state tiles resident in
VMEM instead of XLA's separate elementwise loops, for the three optimizers
that matter at scale:

* **SGD(-momentum)** — `flat_update_fn("sgd", ...)`: weight, grad,
  momentum (and the fp32 master under multi-precision) are read once per
  tile, the whole update rule runs on the registers, and every output is
  written once.
* **Adam** — `flat_update_fn("adam", ...)`: same single pass over five
  tensors (w, g, mean, var [, master]).
* **LAMB** — two passes by data dependency (`lamb_flat_phase1_fn` +
  `lamb_flat_apply_fn`): pass 1 runs the moment update + direction AND
  reduces the per-SEGMENT squared norms (`BucketLayout` segment
  boundaries → per-parameter ‖w‖², ‖g‖²) from the very same VMEM tiles;
  after the tiny cross-rank norm exchange, pass 2 applies the
  trust-ratio-scaled step in one more pass.

Dispatch surfaces (both gated by `use_pallas_flat`):

* the ZeRO flat-shard path — `optimizer._fused_flat_fn` returns these
  wrappers, so `ZeroUpdater` runs them without knowing;
* the per-parameter registry path — `tpu_impl` overrides on
  `sgd_update` / `sgd_mom_update` / `adam_update` /
  `lamb_update_phase1` / `lamb_update_phase2`, taken by the eager
  `optimizer._run_op` on an accelerator context under the registry's
  `MXNET_TPU_USE_PALLAS` gate.

Every wrapper shape/dtype-gates AUTOMATICALLY: an ineligible call (non-f32
per-param weights, integer tensors, empty shards) is counted under
`ops.pallas.fallback.<reason>` and routed to the always-correct XLA
composite — never an error. Eligible dispatches count
`ops.pallas.dispatch(.<kernel>)` and ride a `pallas.<kernel>` telemetry
span (ops/pallas_stats.py); `parse_log --kernels` renders the table.

Numerics: the kernels execute the SAME elementwise operations in the same
order as the XLA composites (`optimizer._fused_flat_xla`, the
optimizer_ops), so SGD/Adam results are bit-identical in interpreter mode
(tests assert equality). LAMB's per-segment norm reduction accumulates
per-tile (Pallas) vs per-slice (XLA), so trust ratios agree only to fp32
round-off — parity tests use a documented tolerance.

Interpreter caveat: `MXNET_FLASH_INTERPRET=1` runs every kernel through
the Pallas interpreter on the CPU backend — parity evidence only, never
perf evidence (the interpreter serializes the grid).
"""
from __future__ import annotations

import functools
import os

import numpy as _np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import optimizer_ops as _opt_ops
from . import pallas_stats as _pstats
from . import registry as _reg
from .pallas_stats import compiler_params as _compiler_params

__all__ = ["use_pallas_flat", "flat_update_fn", "lamb_flat_phase1_fn",
           "lamb_flat_apply_fn"]

_LANES = 128
_SUBLANES = 8
_MAX_TILE_ROWS = 1024    # 1024x128 f32 tile = 512 KB; <=6 operand tiles
                         # + outputs stay well inside the 16 MB VMEM


def _interpret():
    return os.environ.get("MXNET_FLASH_INTERPRET", "0") == "1"


def use_pallas_flat():
    """Is the Pallas optimizer path requested? Interpreter runs always take
    it (that is what they test); compiled runs need the TPU backend plus
    the MXNET_TPU_USE_PALLAS opt-in — same gate shape as the fused-conv
    training kernels."""
    if _interpret():
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return os.environ.get("MXNET_TPU_USE_PALLAS", "0") == "1"


def _flat_geometry(n):
    """(padded_rows, tile_rows, grid) for a flat length-n vector laid out
    as (rows, 128) f32-friendly tiles."""
    rows = max(_SUBLANES, -(-n // _LANES))
    rows = -(-rows // _SUBLANES) * _SUBLANES
    if rows <= _MAX_TILE_ROWS:
        return rows, rows, 1
    rows = -(-rows // _MAX_TILE_ROWS) * _MAX_TILE_ROWS
    return rows, _MAX_TILE_ROWS, rows // _MAX_TILE_ROWS


def _pad2d(flat, rows):
    pad = rows * _LANES - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES)


def _unpad(tile2d, n):
    return tile2d.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# kernels — scalars ride one (1, S) SMEM pack; lr/wd arrive either as
# per-element tiles (the ZeRO flat path: per-parameter multipliers survive
# the flattening) or as two scalar slots (the per-param registry path)
# ---------------------------------------------------------------------------
def _kernel_flat_sgd(*refs, momentum_on, clip_on, mp_on, vec_lrwd):
    it = iter(refs)
    w_ref, g_ref = next(it), next(it)
    m_ref = next(it) if momentum_on else None
    mst_ref = next(it) if mp_on else None
    lr_ref = next(it) if vec_lrwd else None
    wd_ref = next(it) if vec_lrwd else None
    s_ref = next(it)
    w_out = next(it)
    m_out = next(it) if momentum_on else None
    mst_out = next(it) if mp_on else None

    w = w_ref[...]
    w32 = mst_ref[...] if mp_on else w.astype(jnp.float32)
    g32 = g_ref[...].astype(jnp.float32) * s_ref[0, 1]
    if clip_on:
        g32 = jnp.clip(g32, -s_ref[0, 2], s_ref[0, 2])
    wd = wd_ref[...] if vec_lrwd else s_ref[0, 4]
    lr = lr_ref[...] if vec_lrwd else s_ref[0, 3]
    g32 = g32 + wd * w32
    if momentum_on:
        m = m_ref[...].astype(jnp.float32) * s_ref[0, 0] - lr * g32
        m_out[...] = m.astype(m_out.dtype)
        w32n = w32 + m
    else:
        w32n = w32 - lr * g32
    w_out[...] = w32n.astype(w_out.dtype)
    if mp_on:
        mst_out[...] = w32n


def _kernel_flat_adam(*refs, clip_on, mp_on, vec_lrwd):
    it = iter(refs)
    w_ref, g_ref, mean_ref, var_ref = next(it), next(it), next(it), next(it)
    mst_ref = next(it) if mp_on else None
    lr_ref = next(it) if vec_lrwd else None
    wd_ref = next(it) if vec_lrwd else None
    s_ref = next(it)
    w_out, m_out, v_out = next(it), next(it), next(it)
    mst_out = next(it) if mp_on else None

    w = w_ref[...]
    w32 = mst_ref[...] if mp_on else w.astype(jnp.float32)
    g32 = g_ref[...].astype(jnp.float32) * s_ref[0, 5]
    if clip_on:
        g32 = jnp.clip(g32, -s_ref[0, 6], s_ref[0, 6])
    wd = wd_ref[...] if vec_lrwd else s_ref[0, 8]
    lr = lr_ref[...] if vec_lrwd else s_ref[0, 7]
    g32 = g32 + wd * w32
    m = s_ref[0, 0] * mean_ref[...] + s_ref[0, 1] * g32
    v = s_ref[0, 2] * var_ref[...] + s_ref[0, 3] * g32 * g32
    w32n = w32 - lr * m / (jnp.sqrt(v) + s_ref[0, 4])
    w_out[...] = w32n.astype(w_out.dtype)
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)
    if mp_on:
        mst_out[...] = w32n


def _kernel_flat_lamb1(*refs, clip_on, mp_on, bias_corr, vec_wd,
                       with_norms, n_keys, keys_padded):
    it = iter(refs)
    w_ref, g_ref, mean_ref, var_ref = next(it), next(it), next(it), next(it)
    mst_ref = next(it) if mp_on else None
    wd_ref = next(it) if vec_wd else None
    seg_ref = next(it) if with_norms else None
    s_ref = next(it)
    gd_out, m_out, v_out = next(it), next(it), next(it)
    p_out = next(it) if with_norms else None

    w = w_ref[...]
    w32 = mst_ref[...] if mp_on else w.astype(jnp.float32)
    g32 = g_ref[...].astype(jnp.float32) * s_ref[0, 7]
    if clip_on:
        g32 = jnp.clip(g32, -s_ref[0, 8], s_ref[0, 8])
    m = s_ref[0, 0] * mean_ref[...] + s_ref[0, 1] * g32
    v = s_ref[0, 2] * var_ref[...] + s_ref[0, 3] * g32 * g32
    if bias_corr:
        mh = m / s_ref[0, 4]
        vh = v / s_ref[0, 5]
    else:
        mh, vh = m, v
    wd = wd_ref[...] if vec_wd else s_ref[0, 9]
    gdir = mh / (jnp.sqrt(vh) + s_ref[0, 6]) + wd * w32
    gd_out[...] = gdir
    m_out[...] = m.astype(m_out.dtype)
    v_out[...] = v.astype(v_out.dtype)
    if with_norms:
        # per-segment ||w||^2 / ||gdir||^2 partials from the tiles already
        # in VMEM: one statically-unrolled masked reduction per bucket key
        # (padding elements are zeros — they contribute nothing).
        # Scaling caveat: this is O(n_keys x tile) VPU work — fine for
        # conv-weight buckets (few keys) but a many-hundred-key bucket of
        # small BN/bias params degrades toward n_keys sweeps; a one-hot
        # contraction needs (tile, n_keys) VMEM it cannot afford at full
        # tile size. Chunked-dot variant is a kernel-layer-v2 follow-on
        # (ROADMAP item #2).
        seg = seg_ref[...]
        sqw = w32 * w32
        sqg = gdir * gdir
        wp = jnp.stack([jnp.sum(jnp.where(seg == k, sqw, 0.0))
                        for k in range(n_keys)])
        gp = jnp.stack([jnp.sum(jnp.where(seg == k, sqg, 0.0))
                        for k in range(n_keys)])
        zpad = jnp.zeros((keys_padded - n_keys,), jnp.float32)
        p_out[0, 0] = jnp.concatenate([wp, zpad]) if keys_padded > n_keys \
            else wp
        p_out[0, 1] = jnp.concatenate([gp, zpad]) if keys_padded > n_keys \
            else gp


def _kernel_flat_apply(*refs, mp_on, vec_scale):
    it = iter(refs)
    w_ref = next(it)
    mst_ref = next(it) if mp_on else None
    gd_ref = next(it)
    sc_ref = next(it) if vec_scale else None
    s_ref = next(it)
    w_out = next(it)
    mst_out = next(it) if mp_on else None

    w = w_ref[...]
    w32 = mst_ref[...] if mp_on else w.astype(jnp.float32)
    scale = sc_ref[...] if vec_scale else s_ref[0, 0]
    w32n = w32 - scale * gd_ref[...]
    w_out[...] = w32n.astype(w_out.dtype)
    if mp_on:
        mst_out[...] = w32n


# ---------------------------------------------------------------------------
# jitted wrappers: pad/reshape flat operands to (rows, 128) tiles, launch
# ONE pallas_call over the row grid, slice the padding back off
# ---------------------------------------------------------------------------
_CACHE = {}


def _tile_spec(tile_rows):
    return pl.BlockSpec((tile_rows, _LANES), lambda i: (i, 0))


def _scal_spec(n):
    return pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.SMEM)


def _scal_pack(*vals):
    return jnp.stack([jnp.asarray(v, jnp.float32).reshape(())
                      for v in vals]).reshape(1, -1)


def _launch(kernel, tiles, scal, out_dtypes, tile_rows, grid, rows,
            extra_out_specs=(), extra_out_shapes=()):
    cparams = _compiler_params(("arbitrary",))
    in_specs = [_tile_spec(tile_rows) for _ in tiles] + \
        [_scal_spec(scal.shape[1])]
    out_specs = [_tile_spec(tile_rows) for _ in out_dtypes] + \
        list(extra_out_specs)
    out_shapes = [jax.ShapeDtypeStruct((rows, _LANES), dt)
                  for dt in out_dtypes] + list(extra_out_shapes)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=_interpret(),
        **({"compiler_params": cparams} if cparams else {}),
    )(*tiles, scal)


def _flat_sgd_impl(momentum_on, clip_on, mp_on, vec_lrwd):
    def impl(w, g, mom, master, lr, wd, momentum, rescale, clip):
        n = w.shape[0]
        rows, tr, grid = _flat_geometry(n)
        tiles = [_pad2d(w, rows), _pad2d(g, rows)]
        if momentum_on:
            tiles.append(_pad2d(mom, rows))
        if mp_on:
            tiles.append(_pad2d(master, rows))
        if vec_lrwd:
            tiles += [_pad2d(lr, rows), _pad2d(wd, rows)]
            scal = _scal_pack(momentum, rescale, clip, 0.0, 0.0)
        else:
            scal = _scal_pack(momentum, rescale, clip, lr, wd)
        out_dtypes = [w.dtype]
        if momentum_on:
            out_dtypes.append(mom.dtype)
        if mp_on:
            out_dtypes.append(jnp.float32)
        kern = functools.partial(_kernel_flat_sgd, momentum_on=momentum_on,
                                 clip_on=clip_on, mp_on=mp_on,
                                 vec_lrwd=vec_lrwd)
        outs = _launch(kern, tiles, scal, out_dtypes, tr, grid, rows)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        it = iter(outs)
        w_new = _unpad(next(it), n)
        mom_new = _unpad(next(it), n) if momentum_on else mom
        mst_new = _unpad(next(it), n) if mp_on else master
        return w_new, mom_new, mst_new
    return impl


def _flat_adam_impl(clip_on, mp_on, vec_lrwd):
    def impl(w, g, mean, var, master, lr, wd, beta1, omb1, beta2, omb2,
             eps, rescale, clip):
        n = w.shape[0]
        rows, tr, grid = _flat_geometry(n)
        tiles = [_pad2d(w, rows), _pad2d(g, rows), _pad2d(mean, rows),
                 _pad2d(var, rows)]
        if mp_on:
            tiles.append(_pad2d(master, rows))
        if vec_lrwd:
            tiles += [_pad2d(lr, rows), _pad2d(wd, rows)]
            scal = _scal_pack(beta1, omb1, beta2, omb2, eps, rescale, clip,
                              0.0, 0.0)
        else:
            scal = _scal_pack(beta1, omb1, beta2, omb2, eps, rescale, clip,
                              lr, wd)
        out_dtypes = [w.dtype, mean.dtype, var.dtype]
        if mp_on:
            out_dtypes.append(jnp.float32)
        kern = functools.partial(_kernel_flat_adam, clip_on=clip_on,
                                 mp_on=mp_on, vec_lrwd=vec_lrwd)
        outs = _launch(kern, tiles, scal, out_dtypes, tr, grid, rows)
        it = iter(outs)
        w_new = _unpad(next(it), n)
        m_new = _unpad(next(it), n)
        v_new = _unpad(next(it), n)
        mst_new = _unpad(next(it), n) if mp_on else master
        return w_new, m_new, v_new, mst_new
    return impl


def _jitted(key, builder):
    fn = _CACHE.get(key)
    if fn is None:
        fn = _CACHE[key] = jax.jit(builder())
    return fn


def _float_gate(*arrays):
    """Shape/dtype gate shared by every wrapper: floating tensors only,
    nothing empty. Returns a fallback reason or None."""
    for a in arrays:
        if a is None:
            continue
        if not jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
            return "dtype"
        if int(_np.prod(a.shape, dtype=_np.int64)) == 0:
            return "empty"
    return None


def flat_update_fn(kind, momentum_on, clip_on, mp_on):
    """Pallas analog of `optimizer._fused_flat_xla` — same call signature
    per kind, counted dispatch, automatic fallback to the XLA composite
    for ineligible operands."""
    name = "flat_%s" % kind

    if kind == "sgd":
        def wrapper(w, g, mom, master, lr_vec, wd_vec, momentum, rescale,
                    clip):
            reason = _float_gate(w, g, mom)
            if reason:
                from ..optimizer.optimizer import _fused_flat_xla
                _pstats.note_fallback(name, reason)
                return _fused_flat_xla(kind, momentum_on, clip_on, mp_on)(
                    w, g, mom, master, lr_vec, wd_vec, momentum, rescale,
                    clip)
            _pstats.note_dispatch(name)
            with _pstats.kernel_span(name):
                fn = _jitted(("sgd", momentum_on, clip_on, mp_on, True),
                             lambda: _flat_sgd_impl(momentum_on, clip_on,
                                                    mp_on, True))
                return fn(w, g, mom, master, lr_vec, wd_vec, momentum,
                          rescale, clip)
    elif kind == "adam":
        def wrapper(w, g, mean, var, master, lr_vec, wd_vec, beta1, omb1,
                    beta2, omb2, eps, rescale, clip):
            reason = _float_gate(w, g, mean, var)
            if reason:
                from ..optimizer.optimizer import _fused_flat_xla
                _pstats.note_fallback(name, reason)
                return _fused_flat_xla(kind, momentum_on, clip_on, mp_on)(
                    w, g, mean, var, master, lr_vec, wd_vec, beta1, omb1,
                    beta2, omb2, eps, rescale, clip)
            _pstats.note_dispatch(name)
            with _pstats.kernel_span(name):
                fn = _jitted(("adam", clip_on, mp_on, True),
                             lambda: _flat_adam_impl(clip_on, mp_on, True))
                return fn(w, g, mean, var, master, lr_vec, wd_vec, beta1,
                          omb1, beta2, omb2, eps, rescale, clip)
    else:
        raise KeyError(kind)
    return wrapper


# ---------------------------------------------------------------------------
# LAMB: pass 1 (moment update + direction + per-segment norm partials),
# tiny cross-rank norm exchange by the caller, pass 2 (trust-ratio apply)
# ---------------------------------------------------------------------------
def _keys_padded(n_keys):
    return max(_LANES, -(-n_keys // _LANES) * _LANES)


def _lamb1_xla_impl(clip_on, mp_on, bias_corr, segments, n_keys):
    def impl(w, g, mean, var, master, wd_vec, seg_ids, beta1, omb1, beta2,
             omb2, d1, d2, eps, rescale, clip):
        w32 = master if mp_on else w.astype(jnp.float32)
        g32 = g.astype(jnp.float32) * rescale
        if clip_on:
            g32 = jnp.clip(g32, -clip, clip)
        m = beta1 * mean + omb1 * g32
        v = beta2 * var + omb2 * g32 * g32
        if bias_corr:
            mh = m / d1
            vh = v / d2
        else:
            mh, vh = m, v
        gdir = mh / (jnp.sqrt(vh) + eps) + wd_vec * w32
        zero = jnp.zeros((), jnp.float32)
        wp, gp = [], []
        for k in range(n_keys):
            segs = [(s, ln) for (ki, s, ln) in segments if ki == k]
            wp.append(sum((jnp.sum(w32[s:s + ln] * w32[s:s + ln])
                           for s, ln in segs), zero))
            gp.append(sum((jnp.sum(gdir[s:s + ln] * gdir[s:s + ln])
                           for s, ln in segs), zero))
        norms = jnp.stack([jnp.stack(wp), jnp.stack(gp)])
        return gdir, m.astype(mean.dtype), v.astype(var.dtype), norms
    return impl


def _lamb1_pallas_impl(clip_on, mp_on, bias_corr, n_keys):
    kp = _keys_padded(n_keys)

    def impl(w, g, mean, var, master, wd_vec, seg_ids, beta1, omb1, beta2,
             omb2, d1, d2, eps, rescale, clip):
        n = w.shape[0]
        rows, tr, grid = _flat_geometry(n)
        tiles = [_pad2d(w, rows), _pad2d(g, rows), _pad2d(mean, rows),
                 _pad2d(var, rows)]
        if mp_on:
            tiles.append(_pad2d(master, rows))
        tiles += [_pad2d(wd_vec, rows), _pad2d(seg_ids, rows)]
        scal = _scal_pack(beta1, omb1, beta2, omb2, d1, d2, eps, rescale,
                          clip, 0.0)
        kern = functools.partial(
            _kernel_flat_lamb1, clip_on=clip_on, mp_on=mp_on,
            bias_corr=bias_corr, vec_wd=True, with_norms=True,
            n_keys=n_keys, keys_padded=kp)
        outs = _launch(
            kern, tiles, scal, [jnp.float32, mean.dtype, var.dtype],
            tr, grid, rows,
            extra_out_specs=[pl.BlockSpec((1, 2, kp), lambda i: (i, 0, 0))],
            extra_out_shapes=[
                jax.ShapeDtypeStruct((grid, 2, kp), jnp.float32)])
        gdir, m_new, v_new, partials = outs
        norms = jnp.sum(partials, axis=0)[:, :n_keys]
        return _unpad(gdir, n), _unpad(m_new, n), _unpad(v_new, n), norms
    return impl


def lamb_flat_phase1_fn(clip_on, mp_on, bias_corr, segments, n_keys):
    """LAMB pass 1 over a flat shard: moment update + raw direction + the
    per-key squared-norm partials this rank can see. `segments` is the
    static tuple of (key_index, start, length) from
    `BucketSpec.shard_segments`; `seg_ids` the matching per-element key
    index vector. Dispatches Pallas vs XLA like `flat_update_fn`."""
    name = "flat_lamb1"
    segments = tuple(tuple(s) for s in segments)

    def wrapper(w, g, mean, var, master, wd_vec, seg_ids, *scal):
        use_pallas = use_pallas_flat()
        reason = _float_gate(w, g, mean, var) if use_pallas else None
        if use_pallas and not reason:
            _pstats.note_dispatch(name)
            with _pstats.kernel_span(name):
                fn = _jitted(("lamb1p", clip_on, mp_on, bias_corr, n_keys),
                             lambda: _lamb1_pallas_impl(clip_on, mp_on,
                                                        bias_corr, n_keys))
                return fn(w, g, mean, var, master, wd_vec, seg_ids, *scal)
        if use_pallas:
            _pstats.note_fallback(name, reason)
        fn = _jitted(("lamb1x", clip_on, mp_on, bias_corr, segments,
                      n_keys),
                     lambda: _lamb1_xla_impl(clip_on, mp_on, bias_corr,
                                             segments, n_keys))
        return fn(w, g, mean, var, master, wd_vec, seg_ids, *scal)
    return wrapper


def _apply_pallas_impl(mp_on, vec_scale):
    def impl(w, master, gdir, scale):
        n = w.shape[0]
        rows, tr, grid = _flat_geometry(n)
        tiles = [_pad2d(w, rows)]
        if mp_on:
            tiles.append(_pad2d(master, rows))
        tiles.append(_pad2d(gdir, rows))
        if vec_scale:
            tiles.append(_pad2d(scale, rows))
            scal = _scal_pack(0.0)
        else:
            scal = _scal_pack(scale)
        out_dtypes = [w.dtype] + ([jnp.float32] if mp_on else [])
        kern = functools.partial(_kernel_flat_apply, mp_on=mp_on,
                                 vec_scale=vec_scale)
        outs = _launch(kern, tiles, scal, out_dtypes, tr, grid, rows)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        w_new = _unpad(outs[0], n)
        mst_new = _unpad(outs[1], n) if mp_on else master
        return w_new, mst_new
    return impl


def _apply_xla_impl(mp_on):
    def impl(w, master, gdir, scale):
        w32 = master if mp_on else w.astype(jnp.float32)
        w32n = w32 - scale * gdir
        return w32n.astype(w.dtype), (w32n if mp_on else master)
    return impl


def lamb_flat_apply_fn(mp_on, vec_scale=True):
    """LAMB pass 2: w -= scale * direction, where `scale` already carries
    lr x trust-ratio (per element on the flat path, scalar on the
    per-param path)."""
    name = "flat_lamb2"

    def wrapper(w, master, gdir, scale):
        use_pallas = use_pallas_flat()
        reason = _float_gate(w, gdir) if use_pallas else None
        if use_pallas and not reason:
            _pstats.note_dispatch(name)
            with _pstats.kernel_span(name):
                fn = _jitted(("lamb2p", mp_on, vec_scale),
                             lambda: _apply_pallas_impl(mp_on, vec_scale))
                return fn(w, master, gdir, scale)
        if use_pallas:
            _pstats.note_fallback(name, reason)
        fn = _jitted(("lamb2x", mp_on), lambda: _apply_xla_impl(mp_on))
        return fn(w, master, gdir, scale)
    return wrapper


# ---------------------------------------------------------------------------
# per-parameter registry path: tpu_impl overrides dispatched by
# `optimizer._run_op` through `registry.best_fn` on accelerator contexts.
# f32-only — the base ops run their math in the weight's native dtype,
# the kernels in f32, so anything else falls back (counted) for parity.
# ---------------------------------------------------------------------------
def _pp_gate(*arrays):
    for a in arrays:
        if a.dtype != jnp.float32:
            return "dtype"
        if int(_np.prod(a.shape, dtype=_np.int64)) == 0:
            return "empty"
    return None


def _clip_on(clip_gradient):
    return clip_gradient is not None and clip_gradient >= 0


@_reg.get("sgd_update").tpu_impl
def _sgd_update_tpu(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, lazy_update=True):
    reason = _pp_gate(weight, grad)
    if reason:
        _pstats.note_fallback("sgd", reason)
        return _opt_ops.sgd_update(weight, grad, lr, wd=wd,
                                   rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient)
    clip_on = _clip_on(clip_gradient)
    _pstats.note_dispatch("sgd")
    with _pstats.kernel_span("sgd"):
        fn = _jitted(("sgd", False, clip_on, False, False),
                     lambda: _flat_sgd_impl(False, clip_on, False, False))
        w_new, _, _ = fn(weight.reshape(-1), grad.reshape(-1), None, None,
                         lr, wd, 0.0, rescale_grad,
                         clip_gradient if clip_on else 0.0)
    return w_new.reshape(weight.shape)


@_reg.get("sgd_mom_update").tpu_impl
def _sgd_mom_update_tpu(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0,
                        lazy_update=True):
    reason = _pp_gate(weight, grad, mom)
    if reason:
        _pstats.note_fallback("sgd_mom", reason)
        return _opt_ops.sgd_mom_update(weight, grad, mom, lr,
                                       momentum=momentum, wd=wd,
                                       rescale_grad=rescale_grad,
                                       clip_gradient=clip_gradient)
    clip_on = _clip_on(clip_gradient)
    _pstats.note_dispatch("sgd_mom")
    with _pstats.kernel_span("sgd_mom"):
        fn = _jitted(("sgd", True, clip_on, False, False),
                     lambda: _flat_sgd_impl(True, clip_on, False, False))
        w_new, m_new, _ = fn(weight.reshape(-1), grad.reshape(-1),
                             mom.reshape(-1), None, lr, wd, momentum,
                             rescale_grad,
                             clip_gradient if clip_on else 0.0)
    return w_new.reshape(weight.shape), m_new.reshape(mom.shape)


@_reg.get("adam_update").tpu_impl
def _adam_update_tpu(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                     epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0, lazy_update=True):
    reason = _pp_gate(weight, grad, mean, var)
    if reason:
        _pstats.note_fallback("adam", reason)
        return _opt_ops.adam_update(weight, grad, mean, var, lr,
                                    beta1=beta1, beta2=beta2,
                                    epsilon=epsilon, wd=wd,
                                    rescale_grad=rescale_grad,
                                    clip_gradient=clip_gradient)
    clip_on = _clip_on(clip_gradient)
    _pstats.note_dispatch("adam")
    with _pstats.kernel_span("adam"):
        fn = _jitted(("adam", clip_on, False, False),
                     lambda: _flat_adam_impl(clip_on, False, False))
        w_new, m_new, v_new, _ = fn(
            weight.reshape(-1), grad.reshape(-1), mean.reshape(-1),
            var.reshape(-1), None, lr, wd, beta1, 1.0 - beta1, beta2,
            1.0 - beta2, epsilon, rescale_grad,
            clip_gradient if clip_on else 0.0)
    return (w_new.reshape(weight.shape), m_new.reshape(mean.shape),
            v_new.reshape(var.shape))


@_reg.get("lamb_update_phase1").tpu_impl
def _lamb_phase1_tpu(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                     epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                     rescale_grad=1.0, clip_gradient=-1.0):
    reason = _pp_gate(weight, grad, mean, var)
    if reason:
        _pstats.note_fallback("lamb1", reason)
        return _opt_ops.lamb_update_phase1(
            weight, grad, mean, var, beta1=beta1, beta2=beta2,
            epsilon=epsilon, t=t, bias_correction=bias_correction, wd=wd,
            rescale_grad=rescale_grad, clip_gradient=clip_gradient)
    clip_on = _clip_on(clip_gradient)
    _pstats.note_dispatch("lamb1")
    with _pstats.kernel_span("lamb1"):
        def build():
            def impl(w, g, mean_, var_, b1, omb1, b2, omb2, d1, d2, eps,
                     rescale, clip, wd_):
                n = w.shape[0]
                rows, tr, grid = _flat_geometry(n)
                tiles = [_pad2d(w, rows), _pad2d(g, rows),
                         _pad2d(mean_, rows), _pad2d(var_, rows)]
                scal = _scal_pack(b1, omb1, b2, omb2, d1, d2, eps, rescale,
                                  clip, wd_)
                kern = functools.partial(
                    _kernel_flat_lamb1, clip_on=clip_on, mp_on=False,
                    bias_corr=bool(bias_correction), vec_wd=False,
                    with_norms=False, n_keys=0, keys_padded=0)
                gd, m_new, v_new = _launch(
                    kern, tiles, scal,
                    [jnp.float32, mean_.dtype, var_.dtype], tr, grid, rows)
                return (_unpad(gd, n), _unpad(m_new, n), _unpad(v_new, n))
            return impl
        fn = _jitted(("pp_lamb1", clip_on, bool(bias_correction)), build)
        # bias-corr complements in python double, exactly like the base op
        gd, m_new, v_new = fn(
            weight.reshape(-1), grad.reshape(-1), mean.reshape(-1),
            var.reshape(-1), beta1, 1.0 - beta1, beta2, 1.0 - beta2,
            1.0 - beta1 ** t, 1.0 - beta2 ** t, epsilon, rescale_grad,
            clip_gradient if clip_on else 0.0, wd)
    return (gd.reshape(weight.shape), m_new.reshape(mean.shape),
            v_new.reshape(var.shape))


@_reg.get("lamb_update_phase2").tpu_impl
def _lamb_phase2_tpu(weight, g, r1, r2, lr, lower_bound=-1.0,
                     upper_bound=-1.0):
    reason = _pp_gate(weight, g)
    if reason:
        _pstats.note_fallback("lamb2", reason)
        return _opt_ops.lamb_update_phase2(weight, g, r1, r2, lr,
                                           lower_bound=lower_bound,
                                           upper_bound=upper_bound)
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                      jnp.ones_like(r1))
    _pstats.note_dispatch("lamb2")
    with _pstats.kernel_span("lamb2"):
        fn = _jitted(("pp_lamb2",),
                     lambda: _apply_pallas_impl(False, False))
        w_new, _ = fn(weight.reshape(-1), None, g.reshape(-1), lr * ratio)
    return w_new.reshape(weight.shape)
