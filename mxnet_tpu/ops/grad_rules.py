"""Hand-written vjp rules for the hot eager ops — the FGradient layer.

reference: the per-op FGradient attrs of src/operator/tensor/
elemwise_binary_op_basic.cc, elemwise_unary_op_basic.cc,
fully_connected.cc, matrix_op.cc, softmax.cc. The generic tape records
through `jax.vjp`, which re-traces the op on EVERY eager call (~2 ms/op
measured on this box vs ~70 us for the forward). These rules remove the
trace entirely: forward runs plain, backward runs the closed-form
cotangent math. Coverage targets the ops that dominate un-hybridized
training steps; everything else keeps the generic path, and
tests/test_grad_rules.py pins each rule against the generic vjp.

Rule contract (registry.Operator.def_grad):
    rule(cot, out, raw_args, kwargs, nd_positions)
      -> tuple of cotangents aligned with nd_positions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import get as _get


def _unbroadcast(cot, shape):
    """Reduce a broadcasted cotangent back onto `shape` (the reference's
    broadcast backward reduce_sum)."""
    shape = tuple(shape)
    if cot.shape == shape:
        return cot
    extra = cot.ndim - len(shape)
    if extra > 0:
        cot = cot.sum(axis=tuple(range(extra)))
    axes = tuple(i for i, s in enumerate(shape) if s == 1
                 and cot.shape[i] != 1)
    if axes:
        cot = cot.sum(axis=axes, keepdims=True)
    return cot


def _per_arg(cot_fns):
    """Build a rule from per-slot cotangent lambdas f(cot, out, a, b)."""
    def rule(cot, out, raw_args, kwargs, nd_positions):
        a = raw_args[0]
        b = raw_args[1] if len(raw_args) > 1 else None
        outs = []
        for p in nd_positions:
            c = cot_fns[p](cot, out, a, b)
            tgt = raw_args[p]
            outs.append(_unbroadcast(c, jnp.shape(tgt))
                        .astype(jnp.asarray(tgt).dtype))
        return tuple(outs)
    return rule


# -- binary broadcast ------------------------------------------------------
_get("broadcast_add").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot,
    1: lambda cot, out, a, b: cot}))
_get("broadcast_sub").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot,
    1: lambda cot, out, a, b: -cot}))
_get("broadcast_mul").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot * b,
    1: lambda cot, out, a, b: cot * a}))
_get("broadcast_div").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot / b,
    1: lambda cot, out, a, b: -cot * a / (b * b)}))
# ties split 0.5/0.5, matching lax.max/min's vjp (the generic path)
_get("broadcast_maximum").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot * (jnp.asarray(a > b, cot.dtype)
                                     + 0.5 * (a == b)),
    1: lambda cot, out, a, b: cot * (jnp.asarray(b > a, cot.dtype)
                                     + 0.5 * (a == b))}))
_get("broadcast_minimum").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot * (jnp.asarray(a < b, cot.dtype)
                                     + 0.5 * (a == b)),
    1: lambda cot, out, a, b: cot * (jnp.asarray(b < a, cot.dtype)
                                     + 0.5 * (a == b))}))
_get("broadcast_power").def_grad(_per_arg({
    0: lambda cot, out, a, b: cot * b * a ** (jnp.asarray(b) - 1),
    1: lambda cot, out, a, b: cot * out * jnp.log(a)}))

# -- unary -----------------------------------------------------------------
def _unary(name, fn):
    _get(name).def_grad(
        lambda cot, out, raw_args, kwargs, nd_positions, _f=fn:
        (_f(cot, out, raw_args[0])
         .astype(jnp.asarray(raw_args[0]).dtype),))


_unary("negative", lambda cot, out, a: -cot)
_unary("exp", lambda cot, out, a: cot * out)
_unary("log", lambda cot, out, a: cot / a)
_unary("sqrt", lambda cot, out, a: cot / (2.0 * out))
_unary("square", lambda cot, out, a: cot * 2.0 * a)
_unary("tanh", lambda cot, out, a: cot * (1.0 - out * out))
_unary("sigmoid", lambda cot, out, a: cot * out * (1.0 - out))
_unary("relu", lambda cot, out, a: cot * (a > 0))
_unary("abs", lambda cot, out, a: cot * jnp.sign(a))
_unary("rsqrt", lambda cot, out, a: -0.5 * cot * out / a)
_unary("_copyto", lambda cot, out, a: cot)


def _fallback_vjp(opname, raw_args, kwargs, nd_positions, cot):
    """Backward-time jax.vjp recompute — the escape hatch for kwargs a
    closed-form rule does not model. Still removes the FORWARD trace;
    the cost lands only on the (rare) backward through that op."""
    op = _get(opname)
    fixed = list(raw_args)

    def f(*arrs):
        full = list(fixed)
        for p, a in zip(nd_positions, arrs):
            full[p] = a
        return op.fn(*full, **kwargs)
    _, vjp = jax.vjp(f, *[raw_args[p] for p in nd_positions])
    return vjp(cot)


_ACT_GRADS = {
    "relu": lambda cot, out, a: cot * (a > 0),
    "sigmoid": lambda cot, out, a: cot * out * (1.0 - out),
    "tanh": lambda cot, out, a: cot * (1.0 - out * out),
    "softrelu": lambda cot, out, a: cot * jax.nn.sigmoid(a),
    "softsign": lambda cot, out, a: cot / jnp.square(1.0 + jnp.abs(a)),
    "silu": lambda cot, out, a: cot * (lambda s: s + a * s * (1.0 - s))(
        jax.nn.sigmoid(a)),
}
_ACT_GRADS["swish"] = _ACT_GRADS["silu"]


@_get("Activation").def_grad
def _activation_grad(cot, out, raw_args, kwargs, nd_positions):
    a = raw_args[0]
    g = _ACT_GRADS.get(kwargs.get("act_type", "relu"))
    if g is None:  # gelu etc.: recompute via jax.vjp at backward time
        return _fallback_vjp("Activation", raw_args, kwargs, nd_positions,
                             cot)
    return (g(cot, out, a).astype(jnp.asarray(a).dtype),)


# -- linear algebra --------------------------------------------------------
@_get("dot").def_grad
def _dot_grad(cot, out, raw_args, kwargs, nd_positions):
    a, b = raw_args[0], raw_args[1]
    ta = kwargs.get("transpose_a", False)
    tb = kwargs.get("transpose_b", False)
    if a.ndim != 2 or b.ndim != 2:
        # N-D dot: recompute via vjp at backward (uncommon eager shape)
        return _fallback_vjp("dot", raw_args, kwargs, nd_positions, cot)
    if not ta and not tb:
        da, db = cot @ b.T, a.T @ cot
    elif ta and not tb:
        da, db = b @ cot.T, a @ cot
    elif not ta and tb:
        da, db = cot @ b, cot.T @ a
    else:
        da, db = b.T @ cot.T, cot.T @ a.T
    return (da.astype(a.dtype), db.astype(b.dtype))


@_get("FullyConnected").def_grad
def _fc_grad(cot, out, raw_args, kwargs, nd_positions):
    data, weight = raw_args[0], raw_args[1]
    flatten = kwargs.get("flatten", True)
    x = data.reshape(data.shape[0], -1) if (flatten and data.ndim > 2) \
        else data
    dx = (cot @ weight).reshape(data.shape).astype(data.dtype)
    dw = (cot.reshape(-1, cot.shape[-1]).T
          @ x.reshape(-1, x.shape[-1])).astype(weight.dtype)
    outs = [dx, dw]
    if len(nd_positions) > 2:
        red = tuple(range(cot.ndim - 1))
        outs.append(cot.sum(axis=red).astype(raw_args[2].dtype))
    return tuple(outs)


# -- shape ops -------------------------------------------------------------
@_get("reshape").def_grad
def _reshape_grad(cot, out, raw_args, kwargs, nd_positions):
    return (cot.reshape(jnp.shape(raw_args[0])),)


@_get("transpose").def_grad
def _transpose_grad(cot, out, raw_args, kwargs, nd_positions):
    axes = kwargs.get("axes")
    if not axes:
        return (cot.T if cot.ndim == 2 else jnp.transpose(cot),)
    inv = [0] * len(axes)
    for i, ax in enumerate(axes):
        inv[ax] = i
    return (jnp.transpose(cot, inv),)


@_get("Flatten").def_grad
def _flatten_grad(cot, out, raw_args, kwargs, nd_positions):
    return (cot.reshape(jnp.shape(raw_args[0])),)


@_get("expand_dims").def_grad
def _expand_dims_grad(cot, out, raw_args, kwargs, nd_positions):
    return (cot.reshape(jnp.shape(raw_args[0])),)


# -- reductions ------------------------------------------------------------
def _sum_like_rule(scale_by_count):
    def rule(cot, out, raw_args, kwargs, nd_positions):
        a = raw_args[0]
        axis = kwargs.get("axis")
        keepdims = kwargs.get("keepdims", False)
        if axis is None:
            axes = tuple(range(a.ndim))
        elif isinstance(axis, (tuple, list)):
            axes = tuple(ax % a.ndim for ax in axis)
        else:
            axes = (axis % a.ndim,)
        if kwargs.get("exclude"):
            axes = tuple(i for i in range(a.ndim) if i not in axes)
        c = jnp.asarray(cot)
        if not keepdims:
            for ax in sorted(axes):
                c = jnp.expand_dims(c, ax)
        c = jnp.broadcast_to(c, a.shape)
        if scale_by_count:
            n = 1
            for ax in axes:
                n *= a.shape[ax]
            c = c / n
        return (c.astype(a.dtype),)
    return rule


_get("sum").def_grad(_sum_like_rule(False))
_get("mean").def_grad(_sum_like_rule(True))


# -- softmax family --------------------------------------------------------
@_get("softmax").def_grad
def _softmax_grad(cot, out, raw_args, kwargs, nd_positions):
    t = kwargs.get("temperature")
    if (t not in (None, 1.0)) or kwargs.get("use_length") \
            or kwargs.get("length") is not None:
        return _fallback_vjp("softmax", raw_args, kwargs, nd_positions, cot)
    axis = kwargs.get("axis", -1)
    inner = (cot * out).sum(axis=axis, keepdims=True)
    return ((out * (cot - inner)).astype(jnp.asarray(raw_args[0]).dtype),)


@_get("log_softmax").def_grad
def _log_softmax_grad(cot, out, raw_args, kwargs, nd_positions):
    t = kwargs.get("temperature")
    if (t not in (None, 1.0)) or kwargs.get("use_length") \
            or kwargs.get("length") is not None:
        return _fallback_vjp("log_softmax", raw_args, kwargs, nd_positions,
                             cot)
    axis = kwargs.get("axis", -1)
    c = cot - jnp.exp(out) * cot.sum(axis=axis, keepdims=True)
    return (c.astype(jnp.asarray(raw_args[0]).dtype),)


# -- indexing --------------------------------------------------------------
@_get("_internal_getitem").def_grad
def _getitem_grad(cot, out, raw_args, kwargs, nd_positions):
    a = raw_args[0]
    idx = kwargs.get("index")
    if idx is None:  # data[None]: a leading broadcast axis
        return (cot.reshape(jnp.shape(a)).astype(a.dtype),)
    z = jnp.zeros(jnp.shape(a), dtype=cot.dtype)
    return (z.at[idx].add(cot).astype(a.dtype),)
