"""`mx.sym` — the symbolic namespace, codegen'd from the shared op registry.
reference: python/mxnet/symbol/__init__.py."""
import sys as _sys
import types as _types

from .symbol import (Symbol, Variable, var, Group, load, load_json, populate,
                     zeros, ones, arange)
from .executor import Executor

populate(globals())

# mx.sym.random.* sub-namespace (reference: python/mxnet/symbol/random.py)
from .symbol import _make_op as _mk  # noqa: E402
random = _types.ModuleType(__name__ + ".random")
for _pub, _src in [("uniform", "_random_uniform"),
                   ("normal", "_random_normal"),
                   ("randint", "_random_randint"),
                   ("gamma", "_random_gamma"),
                   ("exponential", "_random_exponential"),
                   ("poisson", "_random_poisson"),
                   ("multinomial", "_sample_multinomial"),
                   ("shuffle", "_shuffle")]:
    setattr(random, _pub, _mk(_src))
_sys.modules[random.__name__] = random

# mx.sym.contrib.* sub-namespace (reference: python/mxnet/symbol/contrib.py
# — every `_contrib_*` registered op under its short name, composable into
# graphs exactly like the core ops)
from ..ops import registry as _reg_mod  # noqa: E402
contrib = _types.ModuleType(__name__ + ".contrib")
for _full in list(_reg_mod.list_ops()):
    if _full.startswith("_contrib_"):
        setattr(contrib, _full[len("_contrib_"):], _mk(_full))
# mx.sym.image / mx.sym.linalg / mx.sym.sparse sub-namespaces (reference:
# python/mxnet/symbol/image.py, linalg.py, sparse.py)
image = _types.ModuleType(__name__ + ".image")
for _full in list(_reg_mod.list_ops()):
    if _full.startswith("_image_"):
        setattr(image, _full[len("_image_"):], _mk(_full))
_sys.modules[image.__name__] = image

linalg = _types.ModuleType(__name__ + ".linalg")
for _full in list(_reg_mod.list_ops()):
    if _full.startswith("linalg_"):
        setattr(linalg, _full[len("linalg_"):], _mk(_full))
_sys.modules[linalg.__name__] = linalg

sparse = _types.ModuleType(__name__ + ".sparse")
_all_ops = set(_reg_mod.list_ops())
for _name in ("dot", "elemwise_add", "cast_storage", "zeros_like",
              "square", "sqrt", "abs", "sum", "mean", "clip", "sign",
              "where", "negative"):
    if _name in _all_ops:
        setattr(sparse, _name, _mk(_name))
_sys.modules[sparse.__name__] = sparse

# control-flow contrib ops are F-generic python functions (tracing runs
# through nd with tracer payloads), same objects as nd.contrib's
from ..ndarray.contrib_flow import foreach as _cf_foreach, \
    while_loop as _cf_while_loop, cond as _cf_cond  # noqa: E402
contrib.foreach = _cf_foreach
contrib.while_loop = _cf_while_loop
contrib.cond = _cf_cond
_sys.modules[contrib.__name__] = contrib
