"""Symbol: the declarative graph API.

TPU-native analog of reference python/mxnet/symbol/symbol.py over the NNVM
graph (reference: 3rdparty/tvm/nnvm include/nnvm/symbolic.h, Symbol::Compose,
src/pass/saveload_json.cc). A Symbol is a lightweight DAG node referencing
the SAME op registry as `mx.nd` — one definition per op, visible in both
namespaces (reference: python/mxnet/symbol/register.py codegen).

Execution maps to the imperative layer: `bind`/`simple_bind` build an
Executor whose forward topologically evaluates the graph through NDArray
ops (so autograd supplies backward), and whose jitted fast-path is exactly
`hybridize` (CachedOp ≙ jax.jit). Graph passes of the reference (InferShape,
PlanMemory, Gradient) collapse to jax.eval_shape / XLA buffer assignment /
jax.vjp respectively.

JSON format: `tojson()` emits the reference's NNVM layout {nodes, arg_nodes,
node_row_ptr, heads, attrs} with per-node {"op","name","attrs","inputs"} so
`-symbol.json` files round-trip with the reference ecosystem.
"""
from __future__ import annotations

import ast
import json

import numpy as _np

from .. import ndarray as nd
from ..base import MXNetError, np_dtype
from ..name import NameManager
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "arange"]

_AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean", "running_var")


class Symbol:
    """A node (or node-output slice) in the symbolic graph."""

    def __init__(self, op=None, name=None, inputs=None, attrs=None,
                 kwargs=None, num_outputs=1, out_index=None):
        self._op = op                # None for variables
        self._name = name
        self._inputs = inputs or []  # list[Symbol]
        self._attrs = dict(attrs or {})   # user attrs (__shape__, lr_mult...)
        self._kwargs = dict(kwargs or {})  # op hyper-params
        self._num_outputs = num_outputs
        self._out_index = out_index  # int → this symbol is one output slice
        self._outputs_cache = None
        self._base_ref = None        # sliced symbols: the real base object

    # ------------------------------------------------------------------
    @property
    def name(self):
        if self._out_index is not None and self._num_outputs > 1:
            return "%s_output%d" % (self._name, self._out_index)
        return self._name

    @property
    def op(self):
        return self._op

    def __repr__(self):
        if self._op is None:
            return "<Symbol %s>" % self.name
        return "<Symbol %s>" % self.name

    def __iter__(self):
        return (self[i] for i in range(len(self.list_outputs())))

    def __getitem__(self, index):
        outputs = self.list_outputs()
        if isinstance(index, str):
            idx = outputs.index(index)
            return self[idx]
        if isinstance(index, slice):
            return Group([self[i] for i in range(*index.indices(
                len(outputs)))])
        if self._op == "_group":
            return self._inputs[index]
        if index >= self._num_outputs:
            raise IndexError("Index: %d exceeds the number of outputs: %d." %
                             (index, self._num_outputs))
        if self._num_outputs == 1:
            return self
        sliced = Symbol(self._op, self._name, self._inputs, self._attrs,
                        self._kwargs, self._num_outputs, out_index=index)
        # keep the real base object so graph dedup (topo/tojson) sees ONE
        # node regardless of how many slices reference it
        sliced._base_ref = self if self._out_index is None \
            else self._base_node()
        return sliced

    def __len__(self):
        return len(self.list_outputs())

    # ------------------------------------------------------------------
    # graph traversal
    # ------------------------------------------------------------------
    def _topo(self):
        """Post-order unique node list (node = Symbol with out_index=None)."""
        seen = {}
        order = []

        def visit(s):
            base = s._base_node()
            if id(base) in seen:
                return
            seen[id(base)] = base
            for i in base._inputs:
                visit(i)
            order.append(base)
        visit(self)
        return order

    def _base_node(self):
        if self._out_index is None:
            return self
        if self._base_ref is not None:
            return self._base_ref
        base = Symbol(self._op, self._name, self._inputs, self._attrs,
                      self._kwargs, self._num_outputs)
        self._base_ref = base
        return base

    def _heads(self):
        """Output symbols (for groups: members)."""
        if self._op == "_group":
            out = []
            for s in self._inputs:
                out.extend(s._heads())
            return out
        return [self]

    def list_arguments(self):
        """Free variables in topo order. reference: Symbol.list_arguments."""
        return [s._name for s in self._topo()
                if s._op is None and not s._is_aux() and not s._is_literal()]

    def _is_literal(self):
        return any(k.startswith("__literal") for k in self._attrs)

    def list_auxiliary_states(self):
        """reference: Symbol.list_auxiliary_states — aux states are
        non-differentiable op states (moving stats)."""
        return [s._name for s in self._topo() if s._op is None and
                s._is_aux()]

    def _is_aux(self):
        if self._attrs.get("__aux__") == "True":
            return True
        return str(self._name or "").endswith(_AUX_SUFFIXES)

    def list_inputs(self):
        return [s._name for s in self._topo() if s._op is None]

    def list_outputs(self):
        """reference: Symbol.list_outputs."""
        outs = []
        for h in self._heads():
            if h._num_outputs == 1 or h._out_index is not None:
                outs.append(h.name if h._op else h._name)
            else:
                outs.extend("%s_output%d" % (h._name, i)
                            for i in range(h._num_outputs))
        return outs

    def get_internals(self):
        """reference: Symbol.get_internals — every node as an output."""
        nodes = self._topo()
        outs = []
        for n in nodes:
            if n._op is None:
                outs.append(n)
            else:
                for i in range(n._num_outputs):
                    outs.append(n[i] if n._num_outputs > 1 else n)
        return Group(outs)

    def get_children(self):
        base = self._base_node()
        if not base._inputs:
            return None
        return Group(list(base._inputs))

    # ------------------------------------------------------------------
    # attrs
    # ------------------------------------------------------------------
    def attr(self, key):
        return self._attrs.get(key)

    def list_attr(self):
        return {k: str(v) for k, v in self._attrs.items()}

    def attr_dict(self):
        """{node_name: attrs} for all nodes. reference: Symbol.attr_dict."""
        ret = {}
        for n in self._topo():
            d = {k: str(v) for k, v in n._attrs.items()}
            d.update({k: str(v) for k, v in n._kwargs.items()})
            if d:
                ret[n._name] = d
        return ret

    def _set_attr(self, **kwargs):
        self._attrs.update({k: str(v) for k, v in kwargs.items()})

    # ------------------------------------------------------------------
    # composition
    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: substitute this symbol's free variables.
        reference: Symbol.__call__ → Symbol::Compose."""
        s = self._compose_args(*args, **kwargs)
        return s

    def _compose_args(self, *args, **kwargs):
        name = kwargs.pop("name", None)
        if args and kwargs:
            raise TypeError(
                "compose only accept input Symbols either as positional or "
                "keyword arguments, not both")
        arg_names = self.list_arguments()
        mapping = {}
        if args:
            if len(args) > len(arg_names):
                raise ValueError("too many positional arguments")
            mapping = dict(zip(arg_names, args))
        else:
            for k, v in kwargs.items():
                if not isinstance(v, Symbol):
                    raise TypeError("Compose expect `Symbol` as arguments")
                mapping[k] = v
        out = self._compose_with(mapping)
        if name is not None:
            out._name = name
        return out

    def _compose_with(self, mapping):
        """Return a copy of the graph with variables substituted by name."""
        memo = {}

        def rebuild(s):
            base = s._base_node()
            key = id(base)
            if key in memo:
                new_base = memo[key]
            else:
                if base._op is None and base._name in mapping:
                    new_base = mapping[base._name]._base_node()
                else:
                    new_base = Symbol(
                        base._op, base._name,
                        [rebuild(i) for i in base._inputs],
                        base._attrs, base._kwargs, base._num_outputs)
                memo[key] = new_base
            if s._out_index is not None:
                return new_base[s._out_index]
            return new_base
        return rebuild(self)

    # ------------------------------------------------------------------
    # shape / type inference (reference: MXSymbolInferShape via nnvm pass;
    # here jax.eval_shape runs the same computation abstractly)
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Returns (arg_shapes, out_shapes, aux_shapes).
        reference: Symbol.infer_shape."""
        try:
            res = self._infer_shape_impl(False, *args, **kwargs)
        except Exception as e:
            raise MXNetError("infer_shape error: %s" % e) from e
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        else:
            for k, v in kwargs.items():
                if v is not None:
                    known[k] = tuple(v)

        nodes = self._topo()
        shapes = {}   # node name -> tuple or list of tuples
        dtypes = {}

        def node_out(s):
            base_name = s._name
            return shapes.get(base_name)

        for n in nodes:
            if n._op == "_group":
                continue  # structural node; heads are inferred individually
            if n._op is None:
                if n._is_literal():
                    lit = n._literal_value()
                    if isinstance(lit, float):
                        shapes[n._name] = ()
                    else:
                        shapes[n._name] = tuple(lit.shape)
                    dtypes[n._name] = _np.float32
                elif n._name in known:
                    shapes[n._name] = known[n._name]
                    dtypes[n._name] = _np.float32
                else:
                    sh = n._attrs.get("__shape__")
                    if sh is not None:
                        sh = ast.literal_eval(sh) if isinstance(sh, str) else sh
                        if sh and all(d for d in sh):
                            shapes[n._name] = tuple(sh)
                            dtypes[n._name] = np_dtype(
                                n._attrs.get("__dtype__", "float32"))
                            continue
                    # defer: may be filled by a consumer op's shape hint
                    shapes[n._name] = None
            else:
                in_shapes = []
                ok = True
                for i in n._inputs:
                    s_in = shapes.get(i._name)
                    if isinstance(s_in, list):
                        s_in = s_in[i._out_index or 0]
                    in_shapes.append((s_in, dtypes.get(i._name, _np.float32)))
                if any(s is None for s, _ in in_shapes):
                    # the forward half of the reference's bidirectional
                    # FInferShape: fill parameter shapes from data shapes
                    hint = _reg.get(n._op).shape_hint
                    if hint is not None:
                        filled = hint([s for s, _ in in_shapes], n._kwargs)
                        for i, new_shape, (old, dt) in zip(
                                n._inputs, filled, in_shapes):
                            if old is None and new_shape is not None:
                                shapes[i._name] = tuple(new_shape)
                        in_shapes = [
                            (shapes.get(i._name) if not isinstance(
                                shapes.get(i._name), list) else
                             shapes.get(i._name)[i._out_index or 0], dt)
                            for i, (_, dt) in zip(n._inputs, in_shapes)]
                    ok = all(s is not None for s, _ in in_shapes)
                if not ok:
                    if partial:
                        shapes[n._name] = None
                        continue
                    missing = [i._name for i, (s, _) in
                               zip(n._inputs, in_shapes) if s is None]
                    raise MXNetError(
                        "cannot infer shape: op %s (%s) has inputs with "
                        "unknown shapes: %s" % (n._name, n._op, missing))
                op = _reg.get(n._op)
                abstract = [jax.ShapeDtypeStruct(s, d) for s, d in in_shapes]
                kw = dict(n._kwargs)
                if op.random:
                    kw.setdefault("key", jax.random.key(0))

                def f(*arrs):
                    return op.fn(*arrs, **kw)
                out = jax.eval_shape(f, *abstract)
                if isinstance(out, (tuple, list)):
                    shapes[n._name] = [tuple(o.shape) for o in out]
                    dtypes[n._name] = out[0].dtype
                else:
                    shapes[n._name] = tuple(out.shape)
                    dtypes[n._name] = out.dtype

        def get_for(s):
            sh = shapes.get(s._name)
            if isinstance(sh, list):
                return sh[s._out_index or 0]
            return sh

        arg_shapes = [shapes.get(n) if not isinstance(shapes.get(n), list)
                      else shapes.get(n)[0] for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        out_shapes = []
        for h in self._heads():
            sh = shapes.get(h._name)
            if isinstance(sh, list):
                if h._out_index is not None:
                    out_shapes.append(sh[h._out_index])
                else:
                    out_shapes.extend(sh)
            else:
                out_shapes.append(sh)
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Simplified dtype propagation (float32 default)."""
        arg_names = self.list_arguments()
        dt = _np.float32
        if args:
            for a in args:
                if a is not None:
                    dt = np_dtype(a)
                    break
        elif kwargs:
            dt = np_dtype(list(kwargs.values())[0])
        return ([dt] * len(arg_names), [dt] * len(self.list_outputs()),
                [_np.float32] * len(self.list_auxiliary_states()))

    # ------------------------------------------------------------------
    # evaluation / binding
    # ------------------------------------------------------------------
    def eval_with(self, feed, ctx=None):
        """Evaluate through NDArray ops (autograd-aware). Returns one
        NDArray or a list."""
        node_vals = {}
        for n in self._topo():
            if n._op == "_group":
                continue  # structural node; heads evaluated individually
            if n._op is None:
                lit = n._literal_value(ctx)
                if lit is not None:
                    node_vals[n._ident()] = lit
                elif n._name not in feed:
                    raise MXNetError("eval is missing input %s" % n._name)
                else:
                    node_vals[n._ident()] = feed[n._name]
            else:
                ins = []
                for i in n._inputs:
                    v = node_vals[i._ident()]
                    if isinstance(v, list) and i._out_index is not None:
                        v = v[i._out_index]
                    elif isinstance(v, list) and len(v) == 1:
                        v = v[0]
                    ins.append(v)
                kw = {k: _parse_attr(v) for k, v in n._kwargs.items()}
                node_vals[n._ident()] = nd.invoke(n._op, *ins, **kw)
        outs = []
        for h in self._heads():
            v = node_vals[h._ident()]
            if isinstance(v, list):
                if h._out_index is not None:
                    outs.append(v[h._out_index])
                else:
                    outs.extend(v)
            else:
                outs.append(v)
        return outs[0] if len(outs) == 1 else outs

    def _ident(self):
        # identity key for a base node: (op, name) is unique per graph
        return (self._op, self._name)

    def _literal_value(self, ctx=None):
        """Materialize literal-constant variables (scalars, sym.zeros...)."""
        a = self._attrs
        if "__literal__" in a:
            return float(a["__literal__"])
        if "__literal_zeros__" in a:
            return nd.zeros(ast.literal_eval(a["__literal_zeros__"]), ctx=ctx)
        if "__literal_ones__" in a:
            return nd.ones(ast.literal_eval(a["__literal_ones__"]), ctx=ctx)
        if "__literal_arange__" in a:
            start, stop, step = ast.literal_eval(a["__literal_arange__"])
            return nd.arange(start, stop, step, ctx=ctx)
        return None

    def eval(self, ctx=None, **kwargs):
        """reference: Symbol.eval — returns list of NDArrays."""
        out = self.eval_with(kwargs, ctx)
        return out if isinstance(out, list) else [out]

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None,
             compile_graph=None):
        """reference: Symbol.bind → Executor. `compile_graph` pins the
        whole-graph compiler on/off for this executor (None = the
        MXNET_TPU_WHOLE_GRAPH gate)."""
        from .executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        compile_graph=compile_graph)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, compile_graph=None,
                    **kwargs):
        """Allocate arrays from inferred shapes and bind.
        reference: Symbol.simple_bind → MXExecutorSimpleBindEx."""
        from .executor import Executor
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        type_dict = type_dict or {}
        # variables may pin their dtype via the __dtype__ attr (e.g. the
        # int8 params quantize_model emits) — honor it unless overridden
        var_dtypes = {n._name: n._attrs["__dtype__"]
                      for n in self._topo()
                      if n._op is None and "__dtype__" in n._attrs}
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if shape is None:
                raise MXNetError("simple_bind could not infer shape for "
                                 "argument %s" % name)
            args[name] = nd.zeros(shape, ctx=ctx,
                                  dtype=type_dict.get(
                                      name, var_dtypes.get(name,
                                                           _np.float32)))
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            aux[name] = nd.zeros(shape, ctx=ctx,
                                 dtype=type_dict.get(
                                     name, var_dtypes.get(name,
                                                          _np.float32)))
        args_grad = None
        if grad_req != "null":
            args_grad = {name: nd.zeros(a.shape, ctx=ctx, dtype=a.dtype)
                         for name, a in args.items()}
        return Executor(self, ctx, args, args_grad, grad_req, aux,
                        compile_graph=compile_graph)

    # ------------------------------------------------------------------
    # serialization (reference: nnvm src/pass/saveload_json.cc)
    # ------------------------------------------------------------------
    def tojson(self, remove_amp_cast=True):
        nodes_list = self._topo()
        index = {n._ident(): i for i, n in enumerate(nodes_list)}
        nodes = []
        arg_nodes = []
        for i, n in enumerate(nodes_list):
            if n._op is None:
                arg_nodes.append(i)
                entry = {"op": "null", "name": n._name, "inputs": []}
                if n._attrs:
                    entry["attrs"] = {k: str(v) for k, v in n._attrs.items()}
            else:
                entry = {
                    "op": n._op, "name": n._name,
                    "attrs": {k: str(v) for k, v in n._kwargs.items()},
                    "inputs": [[index[i_._ident()], i_._out_index or 0, 0]
                               for i_ in n._inputs]}
            nodes.append(entry)
        heads = []
        for h in self._heads():
            hi = index[h._ident()]
            heads.append([hi, h._out_index or 0, 0])
        graph = {
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10900]},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname, remove_amp_cast=True):
        """reference: Symbol.save → `-symbol.json`."""
        with open(fname, "w") as f:
            f.write(self.tojson(remove_amp_cast=remove_amp_cast))

    # ------------------------------------------------------------------
    # operators — route through the shared op registry
    # ------------------------------------------------------------------
    def __add__(self, other):
        return _make_op("broadcast_add")(self, other)

    def __radd__(self, other):
        return _make_op("broadcast_add")(self, other)

    def __sub__(self, other):
        return _make_op("broadcast_sub")(self, other)

    def __rsub__(self, other):
        return _make_op("broadcast_sub")(other, self)

    def __mul__(self, other):
        return _make_op("broadcast_mul")(self, other)

    def __rmul__(self, other):
        return _make_op("broadcast_mul")(self, other)

    def __truediv__(self, other):
        return _make_op("broadcast_div")(self, other)

    def __rtruediv__(self, other):
        return _make_op("broadcast_div")(other, self)

    def __pow__(self, other):
        return _make_op("broadcast_power")(self, other)

    def __neg__(self):
        return _make_op("negative")(self)

    def __eq__(self, other):
        return _make_op("broadcast_equal")(self, other)

    def __ne__(self, other):
        return _make_op("broadcast_not_equal")(self, other)

    def __lt__(self, other):
        return _make_op("broadcast_lesser")(self, other)

    def __le__(self, other):
        return _make_op("broadcast_lesser_equal")(self, other)

    def __gt__(self, other):
        return _make_op("broadcast_greater")(self, other)

    def __ge__(self, other):
        return _make_op("broadcast_greater_equal")(self, other)

    __hash__ = object.__hash__

    # method-style ops used by user code and layers
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        return _make_op("reshape")(self, shape=shape)

    def sum(self, axis=None, keepdims=False):
        return _make_op("sum")(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return _make_op("mean")(self, axis=axis, keepdims=keepdims)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return _make_op("transpose")(self, axes=axes if axes else None)

    def swapaxes(self, dim1, dim2):
        return _make_op("swapaxes")(self, dim1=dim1, dim2=dim2)

    def astype(self, dtype):
        return _make_op("cast")(self, dtype=np_dtype(dtype))

    def slice_axis(self, axis, begin, end):
        return _make_op("slice_axis")(self, axis=axis, begin=begin, end=end)

    def expand_dims(self, axis):
        return _make_op("expand_dims")(self, axis=axis)

    def flatten(self):
        return _make_op("flatten")(self)

    def square(self):
        return _make_op("square")(self)

    def sqrt(self):
        return _make_op("sqrt")(self)

    def exp(self):
        return _make_op("exp")(self)

    def log(self):
        return _make_op("log")(self)

    def abs(self):
        return _make_op("abs")(self)

    def softmax(self, axis=-1):
        return _make_op("softmax")(self, axis=axis)

    def log_softmax(self, axis=-1):
        return _make_op("log_softmax")(self, axis=axis)

    def dot(self, other, **kwargs):
        return _make_op("dot")(self, other, **kwargs)

    @property
    def T(self):
        return self.transpose()


def _parse_attr(v):
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable. reference: symbol.py (var/Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable `name`")
    from ..attribute import current as _attr_current
    attrs = _attr_current()  # active AttrScope attrs; explicit ones win
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype).name)
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        attrs["__init__"] = init
    if stype is not None:
        attrs["__storage_type__"] = str(stype)
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            attrs[k] = str(v)
    return Symbol(op=None, name=name, attrs=attrs)


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol.
    reference: symbol.py (Group)."""
    if not symbols or any(not isinstance(sym, Symbol) for sym in symbols):
        raise TypeError("Expected a list of symbols as input")
    return Symbol(op="_group", name="_group",
                  inputs=[s for s in symbols])


def load_json(json_str):
    """Rebuild a Symbol from NNVM JSON. reference: sym.load_json."""
    graph = json.loads(json_str)
    raw_nodes = graph["nodes"]
    built = []
    for entry in raw_nodes:
        if entry["op"] == "null":
            built.append(Variable(entry["name"],
                                  attr=entry.get("attrs", {})))
        else:
            ins = []
            for (src, out_i, _) in entry["inputs"]:
                s = built[src]
                # slot 0 of a multi-output node still needs slicing — the
                # bare symbol is the whole output group
                if s._num_outputs > 1:
                    s = s[out_i]
                ins.append(s)
            kwargs = {k: _parse_attr(v)
                      for k, v in entry.get("attrs", {}).items()}
            op = _reg.get(entry["op"])
            n_out = op.num_outputs or int(kwargs.get(
                "num_outputs", kwargs.get("num_weights", 1)))
            node = Symbol(entry["op"], entry["name"], ins,
                          kwargs=kwargs, num_outputs=n_out)
            built.append(node)
    heads = []
    for (idx, out_i, _) in graph["heads"]:
        s = built[idx]
        if s._num_outputs > 1:
            s = s[out_i]
        heads.append(s)
    return heads[0] if len(heads) == 1 else Group(heads)


def load(fname):
    """reference: sym.load."""
    with open(fname) as f:
        return load_json(f.read())


# ---------------------------------------------------------------------------
# op namespace codegen (reference: python/mxnet/symbol/register.py)
# ---------------------------------------------------------------------------
# Tensor-input parameter names recognized in op signatures. The reference
# gets the tensor-argument list from NNVM op registration (ListArguments);
# here it is derived from the registered fn's signature prefix.
_TENSOR_PARAMS = frozenset([
    "data", "weight", "bias", "gamma", "beta", "moving_mean", "moving_var",
    "label", "lhs", "rhs", "parameters", "state", "state_cell", "grid",
    "indices", "index", "condition", "x", "y", "a", "b", "positive",
    "negative", "input1", "input2", "query", "key_arr", "value", "mean",
    "var", "mom", "weight32", "grad", "loc", "rois", "anchors", "score"])


def _op_tensor_slots(op):
    """Ordered tensor-input slot names from the fn signature prefix; None
    for variadic ops (*args)."""
    import inspect
    try:
        sig = inspect.signature(op.fn)
    except (ValueError, TypeError):
        return None
    slots = []
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            return None
        if p.name in _TENSOR_PARAMS:
            slots.append(p.name)
        else:
            break
    return slots


def _auto_var_skip(op_name, slot, kwargs):
    """Slots the reference's ListArguments omits conditionally."""
    if slot == "bias" and kwargs.get("no_bias"):
        return True
    if op_name == "LeakyReLU" and slot == "gamma" and \
            kwargs.get("act_type", "leaky") != "prelu":
        return True
    if op_name == "Deconvolution" and slot == "bias" and \
            kwargs.get("no_bias", True):
        return True
    return False


def _make_op(op_name):
    op = _reg.get(op_name)
    slots = _op_tensor_slots(op)

    def sym_op(*args, name=None, attr=None, **kwargs):
        sym_kwargs = {}
        filled = {}
        extras = []
        pos_inputs = []
        for a in args:
            if isinstance(a, Symbol):
                pos_inputs.append(a)
            elif a is None:
                pos_inputs.append(None)
            else:
                pos_inputs.append(_scalar_const(a))
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                if slots and k in slots:
                    filled[k] = v
                else:
                    extras.append(v)
            elif v is not None:
                sym_kwargs[k] = v
        hint = op_name.lower().strip("_")
        name = NameManager.current.get(name, hint)

        if slots is None or not slots:
            inputs = [i for i in pos_inputs if i is not None] + extras
        else:
            # positional args fill slots in order; then auto-create the
            # reference's auto-variables (`{name}_weight` etc.) for any
            # remaining slot (reference: Symbol::Compose auto-var creation)
            for i, a in enumerate(pos_inputs):
                if a is not None and i < len(slots):
                    filled.setdefault(slots[i], a)
                elif a is not None:
                    extras.append(a)
            inputs = []
            for slot in slots:
                if slot in filled:
                    inputs.append(filled[slot])
                elif _auto_var_skip(op_name, slot, sym_kwargs):
                    continue
                else:
                    v = Variable("%s_%s" % (name, slot))
                    if slot in ("moving_mean", "moving_var"):
                        v._attrs["__aux__"] = "True"
                    inputs.append(v)
            inputs.extend(extras)
        # ops with data-dependent output counts register num_outputs=0;
        # the real count is their own kwarg (split: num_outputs, the
        # multi_* fused optimizer updates: num_weights)
        n_out = op.num_outputs or int(sym_kwargs.get(
            "num_outputs", sym_kwargs.get("num_weights", 1)))
        from ..attribute import current as _attr_current
        merged_attr = _attr_current()
        merged_attr.update(attr or {})
        return Symbol(op_name, name, inputs, attrs=merged_attr,
                      kwargs=sym_kwargs, num_outputs=n_out)

    sym_op.__name__ = op_name.lstrip("_") or op_name
    sym_op.__doc__ = op.doc or ("%s (symbolic, from shared op registry)"
                                % op_name)
    return sym_op


_SCALAR_COUNT = [0]


def _scalar_const(value):
    """Embed a python scalar as a constant node (reference handles scalars
    via *_scalar op variants; a constant node keeps the graph uniform)."""
    name = "_scalarconst%d" % _SCALAR_COUNT[0]
    _SCALAR_COUNT[0] += 1
    s = Symbol("_full_like_scalar", name, [],
               kwargs={"value": float(value)})
    # simpler: treat as variable bound to a literal at eval time
    v = Variable(name)
    v._attrs["__literal__"] = str(float(value))
    return v


def populate(namespace, names=None):
    for op_name in (names or _reg.list_ops()):
        namespace.setdefault(op_name, _make_op(op_name))
    return namespace


def zeros(shape, dtype=None, **kwargs):
    v = Variable(NameManager.current.get(None, "zeros"))
    v._attrs["__literal_zeros__"] = str(tuple(shape) if not isinstance(
        shape, int) else (shape,))
    return v


def ones(shape, dtype=None, **kwargs):
    v = Variable(NameManager.current.get(None, "ones"))
    v._attrs["__literal_ones__"] = str(tuple(shape) if not isinstance(
        shape, int) else (shape,))
    return v


def arange(start, stop=None, step=1.0, ctx=None, dtype=None, **kwargs):
    v = Variable(NameManager.current.get(None, "arange"))
    v._attrs["__literal_arange__"] = str((start, stop, step))
    return v
