"""Executor: a bound symbolic graph.

TPU-native analog of reference src/executor/graph_executor.cc via
python/mxnet/executor.py. `forward` evaluates the graph through NDArray ops
under autograd (recording when is_train), `backward` replays the tape into
the bound grad arrays.

Whole-graph fast path (ISSUE 11): when `MXNET_TPU_WHOLE_GRAPH` is on (the
default), forward/backward dispatch ONE compiled program for the entire
graph — `mx.compiler.GraphProgram` lowers the Symbol through the
graph-pass pipeline and `lower().compile()`s it once (forward, or
forward+backward for training), replacing the per-op dispatch loop.
Anything the pipeline cannot lower (random ops, unknown ops, AMP-wrapped
dispatch) falls back to the op-by-op path below with a counted reason
(`compiler.fallback.<reason>`) — never an error. Memory planning / op
fusion (PlanMemory, bulk exec) remain XLA's job either way.
"""
from __future__ import annotations

import numpy as _np

from .. import autograd
from .. import ndarray as nd
from .. import telemetry as _telem
from ..base import MXNetError, get_env

__all__ = ["Executor"]


class Executor:
    """reference: python/mxnet/executor.py (Executor)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, compile_graph=None):
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()

        if isinstance(args, (list, tuple)):
            if len(args) != len(self._arg_names):
                raise MXNetError("bind: expected %d args, got %d" %
                                 (len(self._arg_names), len(args)))
            self.arg_dict = dict(zip(self._arg_names, args))
        else:
            self.arg_dict = dict(args)
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]

        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, (list, tuple)):
            self.grad_dict = dict(zip(self._arg_names, args_grad))
        else:
            self.grad_dict = dict(args_grad)
        self.grad_arrays = [self.grad_dict.get(n) for n in self._arg_names]

        if aux_states is None:
            self.aux_dict = {}
        elif isinstance(aux_states, (list, tuple)):
            self.aux_dict = dict(zip(self._aux_names, aux_states))
        else:
            self.aux_dict = dict(aux_states)
        self.aux_arrays = [self.aux_dict[n] for n in self._aux_names]

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req)

        self.outputs = []
        self._output_names = symbol.list_outputs()
        self._recorded_heads = None

        # whole-graph compiler state: None = not yet tried, a GraphProgram
        # once built, and `_wg_failed` carries the counted fallback reason
        # that pins this executor to the op-by-op path
        self._compile_graph = compile_graph
        self._wg_program = None
        self._wg_failed = None
        self._wg_grads = None       # name -> raw cotangent, set by fwdbwd
        self._wg_raws = None        # inputs of the last wg training forward

    # ------------------------------------------------------------------
    # whole-graph fast path (mx.compiler)
    # ------------------------------------------------------------------
    def _wg_enabled(self):
        if self._compile_graph is not None:
            return bool(self._compile_graph)
        return bool(get_env("MXNET_TPU_WHOLE_GRAPH"))

    def _wg_fallback(self, reason):
        """Pin this executor to op-by-op dispatch, with the reason counted
        (`compiler.fallback.<reason>`) — the never-erroring contract."""
        self._wg_failed = reason
        self._wg_program = None
        _telem.inc("compiler.fallback")
        _telem.inc("compiler.fallback.%s" % reason)

    def _wg_inputs(self):
        """Flat raw inputs in the program's positional order (args then
        aux), read at call time so `forward(**kwargs)` updates and
        `copy_params_from` are visible."""
        raws = [self.arg_dict[n]._read() for n in self._arg_names]
        raws += [self.aux_dict[n]._read() for n in self._aux_names]
        return tuple(raws)

    def _wg_wanted(self):
        """(names, flat-input indices) of arguments whose gradient the
        bound grad_req asks for — the same condition the op-by-op path
        uses to mark variables."""
        names, idx = [], []
        for i, n in enumerate(self._arg_names):
            if self._grad_req.get(n, "null") != "null" and \
                    self.grad_dict.get(n) is not None:
                names.append(n)
                idx.append(i)
        return names, idx

    def _wg_forward(self, is_train):
        from .. import compiler as _compiler
        from ..ndarray.ndarray import _AMP_WRAP
        if _AMP_WRAP is not None:
            # AMP wraps op fns per-dispatch; the emitted program would
            # bypass the casts — stay op-by-op while AMP is active
            raise _compiler.UnsupportedGraphError("amp_active")
        for arr in list(self.arg_dict.values()) + \
                list(self.aux_dict.values()) + list(self.grad_dict.values()):
            if arr is not None and \
                    getattr(arr, "_stype", "default") != "default":
                # row-sparse grads (Embedding sparse_grad) and sparse
                # inputs keep their storage-aware op-by-op path
                raise _compiler.UnsupportedGraphError("sparse_storage")
        if self._wg_program is None:
            self._wg_program = _compiler.GraphProgram(
                self._symbol,
                on_tpu=self._ctx.device_type in ("gpu", "tpu"),
                label=self._symbol.name)
        prog = self._wg_program
        raws = self._wg_inputs()
        names, idx = self._wg_wanted() if is_train else ([], [])
        if is_train and names:
            outs, grads = prog.run_fwd_bwd(raws, idx)
            self._wg_grads = dict(zip(names, grads))
            self._wg_raws = raws
        else:
            outs = prog.run_forward(raws)
            self._wg_grads = None
            self._wg_raws = None
        self.outputs = [nd.from_jax(o, ctx=self._ctx) for o in outs]
        self._recorded_heads = self.outputs if is_train else None
        return self.outputs

    def _wg_backward(self, out_grads):
        """Write the program-computed gradients into the bound grad
        arrays, honoring grad_req write vs add — the same application
        `autograd.backward` performs for the op-by-op tape."""
        grads = self._wg_grads
        if out_grads is not None:
            # rare path: user-supplied head cotangents — rerun as ONE
            # combined program with the cotangents as inputs
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            cots = tuple(g._read() if isinstance(g, nd.NDArray) else g
                         for g in out_grads)
            names, idx = self._wg_wanted()
            _, flat = self._wg_program.run_fwd_bwd(self._wg_raws, idx,
                                                   head_cots=cots)
            grads = dict(zip(names, flat))
        for name, cot in grads.items():
            buf = self.grad_dict.get(name)
            if buf is None:
                continue
            cot = cot.astype(buf.dtype)
            if self._grad_req.get(name) == "add":
                buf._write(buf._read() + cot)
            else:
                buf._write(cot)
        self._wg_grads = None
        self._wg_raws = None

    def forward(self, is_train=False, **kwargs):
        """reference: Executor.forward — kwargs update bound args first."""
        for name, val in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("Unknown argument %s" % name)
            dst = self.arg_dict[name]
            if isinstance(val, nd.NDArray):
                val.copyto(dst)
            else:
                dst[:] = val

        if self._wg_enabled() and self._wg_failed is None:
            from ..compiler import UnsupportedGraphError
            try:
                return self._wg_forward(is_train)
            except UnsupportedGraphError as e:
                self._wg_fallback(e.reason)
            except Exception as e:  # noqa: BLE001 — counted, never raised
                self._wg_fallback("error:%s" % type(e).__name__)

        feed = dict(self.arg_dict)
        feed.update(self.aux_dict)
        if is_train:
            # mark grads on inputs that want them
            for name, arr in self.arg_dict.items():
                req = self._grad_req.get(name, "null")
                if req != "null" and self.grad_dict.get(name) is not None:
                    arr._grad = self.grad_dict[name]
                    arr._grad_req = req
                    autograd.mark_variable(arr, req)
            with autograd.record():
                out = self._symbol.eval_with(feed, self._ctx)
        else:
            with autograd.pause():
                out = self._symbol.eval_with(feed, self._ctx)
        self.outputs = out if isinstance(out, list) else [out]
        self._recorded_heads = self.outputs if is_train else None
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """reference: Executor.backward."""
        if self._recorded_heads is None:
            raise MXNetError("backward called before forward(is_train=True)")
        if self._wg_grads is not None:
            return self._wg_backward(out_grads)
        if out_grads is None:
            head_grads = None
        else:
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            head_grads = list(out_grads)
        autograd.backward(self._recorded_heads, head_grads)
        return

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: Executor.copy_params_from."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name]) if isinstance(
                    array, nd.NDArray) else self.arg_dict[name].__setitem__(
                        slice(None), array)
            elif not allow_extra_params:
                raise ValueError("Find name \"%s\" that is not in the "
                                 "arguments" % name)
        if aux_params is None:
            return
        for name, array in aux_params.items():
            if name in self.aux_dict:
                array.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise ValueError("Find name %s that is not in the auxiliary "
                                 "states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new shapes. reference: Executor.reshape."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, sh in zip(self._arg_names, arg_shapes):
            old = self.arg_dict[name]
            if tuple(old.shape) == tuple(sh):
                new_args[name] = old
            else:
                new_args[name] = nd.zeros(sh, ctx=self._ctx, dtype=old.dtype)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for name, g in self.grad_dict.items():
                if g is None:
                    continue
                sh = new_args[name].shape
                new_grads[name] = g if tuple(g.shape) == tuple(sh) else \
                    nd.zeros(sh, ctx=self._ctx, dtype=g.dtype)
        new_aux = {}
        for name, sh in zip(self._aux_names, aux_shapes):
            old = self.aux_dict[name]
            new_aux[name] = old if tuple(old.shape) == tuple(sh) else \
                nd.zeros(sh, ctx=self._ctx, dtype=old.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux,
                        compile_graph=self._compile_graph)

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))
