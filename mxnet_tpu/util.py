"""`mx.util` — misc utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

import functools
import os

__all__ = ["large_tensor_scope",
           "makedirs", "getenv", "setenv", "set_np", "reset_np",
           "is_np_array", "is_np_shape", "use_np", "np_array", "np_shape",
           "default_array", "atomic_write", "write_latest_marker",
           "read_latest_marker"]


def makedirs(d):
    os.makedirs(d, exist_ok=True)


# ---------------------------------------------------------------------------
# crash-safe file commit — shared by the checkpoint layers
# (parallel.checkpoint LATEST marker, resilience.run SnapshotCheckpointer)
# ---------------------------------------------------------------------------
def atomic_write(path, data):
    """Write `data` (bytes) to `path` via tmp + fsync + os.replace: a crash
    at any point leaves the previous content or the new one, never a torn
    file."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_latest_marker(root, step):
    """Commit `root`/LATEST naming the newest fully-durable checkpoint
    step. Call strictly AFTER the step's payload is on disk."""
    atomic_write(os.path.join(root, "LATEST"), ("%d\n" % int(step)).encode())


def read_latest_marker(root):
    """The step named by `root`/LATEST, or None (missing/corrupt marker —
    callers fall back to a directory scan; a lost marker never loses
    checkpoints)."""
    try:
        with open(os.path.join(root, "LATEST")) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def getenv(name):
    return os.environ.get(name)


def setenv(name, value):
    os.environ[name] = value


# np-mode switches delegate to the npx module (reference: util.set_np etc.)
def set_np(shape=True, array=True):
    from . import numpy_extension as npx
    npx.set_np(shape=shape, array=array)


def reset_np():
    from . import numpy_extension as npx
    npx.reset_np()


def is_np_array():
    from . import numpy_extension as npx
    return npx.is_np_array()


def is_np_shape():
    from . import numpy_extension as npx
    return npx.is_np_shape()


class _NpScope:
    """Context/decorator setting np semantics inside (reference:
    util.np_array / np_shape scopes). `array`/`shape` are the target flag
    values inside the scope — False turns a mode OFF, None leaves it
    unchanged."""

    def __init__(self, array=None, shape=None):
        self._array, self._shape = array, shape

    def __enter__(self):
        from . import numpy_extension as npx
        self._saved = (npx.is_np_shape(), npx.is_np_array())
        npx.set_np(
            shape=self._saved[0] if self._shape is None else self._shape,
            array=self._saved[1] if self._array is None else self._array)
        return self

    def __exit__(self, *exc):
        from . import numpy_extension as npx
        npx.set_np(shape=self._saved[0], array=self._saved[1])
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with type(self)(self._array, self._shape):
                return fn(*args, **kwargs)
        return wrapper


def np_array(active=True):
    return _NpScope(array=bool(active), shape=None)


def np_shape(active=True):
    return _NpScope(array=None, shape=bool(active))


def use_np(fn):
    """Decorator: run `fn` under both np shape and array semantics."""
    return _NpScope(array=True, shape=True)(fn)


def default_array(source, ctx=None, dtype=None):
    """array() in whichever namespace is active (reference:
    util.default_array)."""
    if is_np_array():
        from . import numpy as np_ns
        return np_ns.array(source, dtype=dtype, ctx=ctx)
    from .ndarray import array
    return array(source, ctx=ctx, dtype=dtype)


import contextlib


def _x64_scope():
    """The x64 context manager under whichever name this jax ships it:
    `jax.enable_x64` on newer releases, `jax.experimental.enable_x64`
    before the promotion (0.4.x). Raises MXNetError with the probe result
    if neither exists — large-tensor mode is then genuinely unavailable."""
    import jax
    cm = getattr(jax, "enable_x64", None)
    if cm is not None:
        return cm(True)
    try:
        from jax.experimental import enable_x64 as _cm
    except ImportError:
        from .base import MXNetError
        raise MXNetError(
            "large_tensor_scope: this jax (%s) exposes neither "
            "jax.enable_x64 nor jax.experimental.enable_x64 — 64-bit "
            "tensor indexing is unavailable"
            % getattr(jax, "__version__", "?"))
    return _cm(True)


@contextlib.contextmanager
def large_tensor_scope():
    """64-bit tensor indexing scope (reference: the
    MXNET_INT64_TENSOR_SIZE build flag — large-tensor support is opt-in
    upstream too). Inside the scope, index arithmetic is 64-bit, so
    writes/gathers/argmax past the 2^31 element boundary are exact.
    Kept scoped rather than global because x64 also flips jax's DEFAULT
    dtypes (python floats become float64), which the TPU-native bf16/f32
    path does not want."""
    with _x64_scope():
        yield
