"""Telemetry-driven comm-schedule autotuning (ISSUE 19).

A *comm schedule* is the pair of knobs the bucketed engine exposes:

* the bucket cap (``MXNET_TPU_COMM_BUCKET_MB``; 0 = per-key escape
  hatch), and
* the flush policy — ``registration`` (reverse-registration order fed
  at step time, the PR 4 engine) vs ``ready`` (event-driven flushing
  from the autograd grad-ready callback, `engine.ready`).

`ScheduleAutotuner` sweeps a candidate grid over the first real training
steps: each candidate is applied for ``steps_per_candidate`` steps, then
scored from `telemetry.overlap_report()` over exactly those steps —
``collective_ms`` down, ``overlap_frac`` up, folded into one exposed-
communication-milliseconds scalar. After the sweep the winner is pinned
(process-wide `engine.set_bucket_mb` + the trainer's flush policy),
announced to the flight ring, and exported as gauges. The chosen
schedule serializes into checkpoint payloads (`schedule_payload` /
`restore_schedule`), so a restart re-applies it with ZERO re-sweep
steps.

Every candidate is safe to sweep live: bucketing (any cap, either
policy) is a reassociation of the SAME per-key arithmetic, so every
swept schedule is bit-identical to the unbucketed baseline — the sweep
changes when collectives launch, never what they compute.

Env knobs::

    MXNET_TPU_COMM_AUTOTUNE=1          enable the sweep (Trainer)
    MXNET_TPU_COMM_AUTOTUNE_STEPS=N    steps per candidate (default 2)
    MXNET_TPU_COMM_AUTOTUNE_CAPS=a,b   bucket-MB grid (default 0,4,25,100)
"""
from __future__ import annotations

import os

__all__ = ["CommSchedule", "ScheduleAutotuner", "autotune_enabled",
           "sweep_budget", "current_schedule", "set_schedule",
           "schedule_payload", "restore_schedule", "POLICIES"]

POLICIES = ("registration", "ready")

_DEFAULT_CAPS_MB = (0.0, 4.0, 25.0, 100.0)
_DEFAULT_STEPS = 2


def autotune_enabled():
    """True when `MXNET_TPU_COMM_AUTOTUNE` asks for a warm-up sweep."""
    return os.environ.get("MXNET_TPU_COMM_AUTOTUNE", "0").lower() \
        not in ("0", "", "false", "off")


def sweep_budget():
    """Steps per candidate (`MXNET_TPU_COMM_AUTOTUNE_STEPS`, default 2)."""
    try:
        return max(1, int(os.environ.get("MXNET_TPU_COMM_AUTOTUNE_STEPS",
                                         _DEFAULT_STEPS)))
    except (TypeError, ValueError):
        return _DEFAULT_STEPS


def _caps_grid():
    raw = os.environ.get("MXNET_TPU_COMM_AUTOTUNE_CAPS", "")
    if not raw.strip():
        return list(_DEFAULT_CAPS_MB)
    out = []
    for part in raw.split(","):
        try:
            out.append(float(part))
        except ValueError:
            pass
    return out or list(_DEFAULT_CAPS_MB)


class CommSchedule:
    """One (bucket_mb, flush policy) point — the unit the autotuner
    sweeps, scores, pins, and checkpoints."""

    __slots__ = ("bucket_mb", "policy", "score", "source")

    def __init__(self, bucket_mb, policy, score=None, source="manual"):
        if policy not in POLICIES:
            raise ValueError("flush policy must be one of %s, got %r"
                             % (POLICIES, policy))
        self.bucket_mb = float(bucket_mb)
        self.policy = str(policy)
        self.score = None if score is None else float(score)
        self.source = str(source)

    def apply(self):
        """Pin this schedule's bucket cap process-wide. Returns the
        previous override (for restore); the flush policy is read by the
        Trainer via `current_schedule()`."""
        from . import set_bucket_mb
        return set_bucket_mb(self.bucket_mb)

    def describe(self):
        return "%gMB/%s" % (self.bucket_mb, self.policy)

    def to_payload(self):
        return {"schedule_format": 1, "bucket_mb": self.bucket_mb,
                "policy": self.policy, "score": self.score,
                "source": self.source}

    @classmethod
    def from_payload(cls, payload):
        if int(payload.get("schedule_format", -1)) != 1:
            raise ValueError("unsupported comm-schedule payload %r"
                             % (payload,))
        return cls(payload["bucket_mb"], payload["policy"],
                   score=payload.get("score"),
                   source=payload.get("source", "checkpoint"))

    def __eq__(self, other):
        return (isinstance(other, CommSchedule)
                and self.bucket_mb == other.bucket_mb
                and self.policy == other.policy)

    def __repr__(self):
        return ("CommSchedule(%s, score=%s, source=%s)"
                % (self.describe(), self.score, self.source))


# process-wide chosen schedule — what checkpoints carry and restores pin
_CURRENT = None


def current_schedule():
    return _CURRENT


def set_schedule(schedule, announce=False):
    """Pin `schedule` process-wide (None clears). Applies the bucket cap,
    exports gauges, and (optionally) announces to the flight ring."""
    global _CURRENT
    _CURRENT = schedule
    if schedule is None:
        from . import set_bucket_mb
        set_bucket_mb(None)
        return None
    schedule.apply()
    from .. import telemetry as _telem
    if _telem.ENABLED:
        _telem.set_gauge("comm.schedule.bucket_mb", schedule.bucket_mb)
        _telem.set_gauge("comm.schedule.ready",
                         1.0 if schedule.policy == "ready" else 0.0)
    if announce:
        from ..telemetry import flight
        flight.note_event("autotune", "comm schedule %s (score=%s, %s)"
                          % (schedule.describe(), schedule.score,
                             schedule.source))
    return schedule


def schedule_payload():
    """The chosen schedule as a checkpointable dict, or None — callers
    splice this into their checkpoint trees (ResilientRunner, Trainer
    save_states) so restarts skip the sweep."""
    return None if _CURRENT is None else _CURRENT.to_payload()


def restore_schedule(payload):
    """Re-pin a checkpointed schedule (no-op on None). Returns the
    `CommSchedule` — the restart path to a ZERO-step sweep."""
    if not payload:
        return None
    sched = CommSchedule.from_payload(payload)
    sched.source = "checkpoint"
    return set_schedule(sched, announce=True)


class ScheduleAutotuner:
    """Drives the sweep from inside the training loop. Per step::

        sched = tuner.current()     # schedule for THIS step (trainer
                                    # applies cap + flush policy)
        ... run the step ...
        tuner.on_step_end()         # advance; scores + pins when done

    `done` flips once the winner is pinned (or immediately when
    constructed from a checkpointed schedule — `sweep_steps == 0`)."""

    def __init__(self, candidates=None, steps_per_candidate=None,
                 site="trainer.step"):
        if candidates is None:
            candidates = [CommSchedule(mb, pol, source="sweep")
                          for mb in _caps_grid() for pol in POLICIES]
        self.candidates = list(candidates)
        if not self.candidates:
            raise ValueError("autotuner needs at least one candidate")
        self.steps_per = (sweep_budget() if steps_per_candidate is None
                          else max(1, int(steps_per_candidate)))
        self.site = site
        self.results = []          # [(CommSchedule, metrics dict)]
        self.sweep_steps = 0       # steps spent sweeping (0 after restore)
        self._idx = 0
        self._step_in_candidate = 0
        self.chosen = None

    @classmethod
    def restored(cls, schedule, site="trainer.step"):
        """An autotuner that is already done: the checkpointed schedule
        is the winner and zero sweep steps will run."""
        tuner = cls(candidates=[schedule], site=site)
        tuner.chosen = schedule
        return tuner

    @property
    def done(self):
        return self.chosen is not None

    def current(self):
        """The schedule the NEXT step must run under."""
        if self.chosen is not None:
            return self.chosen
        return self.candidates[self._idx]

    @staticmethod
    def score(metrics):
        """Exposed communication milliseconds — lower is better. The
        overlap report's ``collective_ms`` is host time on the collective
        path and ``overlap_frac`` is the share of the comm phase the host
        spent OFF that path, so ``collective_ms * (1 - overlap_frac)`` is
        the un-hidden remainder; the tiny ``collective_ms`` tie-break
        prefers the schedule that also shrank total collective time."""
        coll = float(metrics.get("collective_ms", 0.0))
        frac = metrics.get("overlap_frac")
        frac = 0.0 if frac is None else float(frac)
        return coll * (1.0 - frac) + 1e-3 * coll

    def on_step_end(self):
        """Advance the sweep by one completed step. Scores the candidate
        after its budget, pins the winner after the last candidate.
        Returns the chosen schedule once done, else None."""
        if self.chosen is not None:
            return self.chosen
        self.sweep_steps += 1
        self._step_in_candidate += 1
        if self._step_in_candidate < self.steps_per:
            return None
        from .. import telemetry as _telem
        report = _telem.overlap_report(site=self.site, limit=self.steps_per)
        cand = self.candidates[self._idx]
        metrics = dict(report.get("summary") or {})
        cand.score = self.score(metrics)
        self.results.append((cand, metrics))
        self._idx += 1
        self._step_in_candidate = 0
        if self._idx < len(self.candidates):
            return None
        best = min(self.results, key=lambda cm: cm[0].score)[0]
        best.source = "autotune"
        self.chosen = best
        set_schedule(best, announce=True)
        from .. import telemetry as _telem2
        if _telem2.ENABLED:
            _telem2.inc("comm.autotune.sweeps")
            _telem2.set_gauge("comm.autotune.sweep_steps",
                              float(self.sweep_steps))
        return best
