"""Readiness-ordered bucket assembly (ISSUE 19).

`GradBucketer` packs gradients in whatever order the caller feeds them —
the trainer approximates backward-completion order by feeding reverse
registration order, but nothing launches until the caller has every
grad in hand. `ReadyScheduler` closes that gap: it is fed from the
autograd grad-ready callback (`autograd.add_grad_ready_hook`) the moment
each parameter's pullback completes, and hands completed buckets to a
dispatch function immediately — so the first collective launches while
the rest of backward is still running.

Two assembly modes:

* **free** (``layout=None``): greedy size-capped packing with one OPEN
  bucket PER DTYPE. Readiness interleaves dtypes arbitrarily; a single
  open bucket would degenerate into ``dtype_split`` flushes the
  registration path never saw, so each dtype packs independently.
  Capacity flushes count ``comm.bucket.flush_reason.ready``; `drain()`
  flushes the partial tails as ``final``. ``cap_bytes=0`` is the
  per-key escape hatch: every `add` dispatches a single-key bucket
  immediately, so the per-key ``comm.key[k]`` spans reflect true launch
  order instead of registration order.

* **frozen** (``layout=BucketLayout``): bucket membership is fixed — a
  bucket dispatches the moment ALL its members have arrived, possibly
  out of bucket-index order. The `Bucket` handed to dispatch is built in
  the spec's canonical key order regardless of arrival order, so the
  packed flat vector is byte-identical to the registration-ordered path
  (bit-exact parity by construction) and every rank of a distributed
  job launches identical segment collectives. This is the ZeRO / dist
  mode: sharded state, residuals, and checkpoints all key on the frozen
  layout, only the LAUNCH ORDER floats with readiness.

Single-threaded by design: the autograd hook fires on the thread running
`backward()`, and `drain()` runs on the same thread at step time.
"""
from __future__ import annotations

__all__ = ["ReadyScheduler"]


def _in_backward():
    from .. import autograd
    return autograd.in_backward()


class ReadyScheduler:
    """Feed `add(key, raw)` in gradient-readiness order; `dispatch_fn`
    fires the moment a bucket completes. See module docstring for the
    free vs frozen assembly modes.

    ``dispatch_fn(bucket, spec)`` — ``spec`` is the `BucketSpec` in
    frozen mode, ``None`` in free mode.

    Counters: flushed buckets tick the standard ``comm.bucket.*`` family
    (reason ``ready`` for readiness flushes, ``final`` for drain tails);
    ``comm.ready.flush_during_backward`` counts dispatches that happened
    while `autograd.backward` was still replaying the tape, and
    ``comm.ready.first_flush_before_backward_end`` ticks once per
    add/drain cycle when the FIRST dispatch beat backward's end — the
    overlap proof the bench asserts on.
    """

    def __init__(self, dispatch_fn, cap_bytes=None, layout=None):
        from . import bucket_bytes
        self._dispatch_fn = dispatch_fn
        self.cap = bucket_bytes() if cap_bytes is None else int(cap_bytes)
        self.layout = layout
        self.dispatched = 0
        self._first_dispatch_done = False
        if layout is not None:
            self._spec_by_key = {}
            for spec in layout:
                for k in spec.keys:
                    self._spec_by_key[k] = spec
            self._pending = {}      # spec.index -> {key: raw}
        else:
            self._open = {}         # dtype -> (items, nbytes)

    # -- dispatch plumbing ---------------------------------------------------
    def _dispatch(self, bucket, spec=None):
        from .. import telemetry as _telem
        if _telem.ENABLED and _in_backward():
            _telem.inc("comm.ready.flush_during_backward")
            if not self._first_dispatch_done:
                _telem.inc("comm.ready.first_flush_before_backward_end")
        self._first_dispatch_done = True
        self.dispatched += 1
        self._dispatch_fn(bucket, spec)

    # -- free mode -----------------------------------------------------------
    def _add_free(self, key, raw):
        import numpy as _np
        from . import Bucket, _count_bucket, _nbytes
        nbytes = _nbytes(raw)
        if self.cap == 0:
            # per-key escape hatch, now readiness-ordered (ISSUE 19 fix)
            self._dispatch(_count_bucket(Bucket([(key, raw)], "ready")))
            return 1
        if nbytes >= self.cap:
            # at/above the cap: never merged, never split — its own bucket
            self._dispatch(_count_bucket(Bucket([(key, raw)], "oversize")))
            return 1
        dt = _np.dtype(raw.dtype)
        items, held = self._open.get(dt, ([], 0))
        n = 0
        if items and held + nbytes > self.cap:
            self._dispatch(_count_bucket(Bucket(items, "ready")))
            items, held = [], 0
            n = 1
        items.append((key, raw))
        self._open[dt] = (items, held + nbytes)
        return n

    # -- frozen mode ---------------------------------------------------------
    def _add_frozen(self, key, raw):
        from . import Bucket, _count_bucket
        key = str(key)
        spec = self._spec_by_key.get(key)
        if spec is None:
            raise ValueError(
                "ReadyScheduler: key %r is not in the frozen bucket layout "
                "(layout keys: %s) — a changed parameter set needs a new "
                "layout" % (key, self._spec_by_key and
                            sorted(self._spec_by_key)[:8]))
        got = self._pending.setdefault(spec.index, {})
        got[key] = raw
        if len(got) < len(spec.keys):
            return 0
        del self._pending[spec.index]
        # canonical spec order, NOT arrival order: the packed flat vector
        # is identical to the registration path's, bit for bit
        bucket = Bucket([(k, got[k]) for k in spec.keys], "ready")
        self._dispatch(_count_bucket(bucket), spec)
        return 1

    # -- public API ----------------------------------------------------------
    def add(self, key, raw):
        """Feed one finalized gradient. Returns the number of buckets
        dispatched by this call (0 or more). Empty/None grads are skipped
        (``comm.bucket.skipped``) — in frozen mode they would stall the
        bucket forever, which `drain()` reports."""
        from .. import telemetry as _telem
        if raw is None or int(raw.size) == 0:
            _telem.inc("comm.bucket.skipped")
            return 0
        if self.layout is not None:
            return self._add_frozen(key, raw)
        return self._add_free(key, raw)

    def drain(self):
        """End of the readiness stream (step time). Free mode flushes the
        partial per-dtype tails (reason ``final``); frozen mode raises if
        any bucket is still missing members — the frozen-layout guard.
        Returns the number of buckets dispatched and re-arms the
        first-flush counter for the next step."""
        from . import Bucket, _count_bucket
        n = 0
        if self.layout is not None:
            if self._pending:
                missing = {}
                for idx, got in sorted(self._pending.items()):
                    spec = next(s for s in self.layout if s.index == idx)
                    missing[idx] = [k for k in spec.keys if k not in got]
                raise ValueError(
                    "ReadyScheduler: frozen layout drained with incomplete "
                    "buckets (missing grads): %s" % (missing,))
        else:
            for dt in sorted(self._open, key=str):
                items, _ = self._open[dt]
                if items:
                    self._dispatch(_count_bucket(Bucket(items, "final")))
                    n += 1
            self._open = {}
        self._first_dispatch_done = False
        return n
