"""`mx.engine` — execution-engine controls + the bucketed gradient-comm
engine.

reference: python/mxnet/engine.py (bulk, set_bulk_size) batches engine pushes
into bulked segments so many small ops ride one engine dispatch, and the
async dependency engine overlaps kvstore pushes with the tail of backward.
Under XLA the *compute* half of that story is free (dispatch is async,
fusion happens in the compiler) — but the *communication* half is not: one
collective per parameter still pays per-launch latency N times, and
small-tensor collectives can't saturate ICI/DCN.

This module is the TPU-native analog of the reference's bulked engine
segments for the gradient path:

* `GradBucketer` packs gradients — callers feed them in reverse-registration
  order, approximating backward completion order — into size-capped flat
  buckets (`MXNET_TPU_COMM_BUCKET_MB`, default 25 MB; 0 restores the
  per-parameter path). Buckets are single-dtype; a gradient at or above the
  cap travels alone.
* `fused_bucket_fn` compiles ONE flatten -> comm -> unflatten XLA program
  per bucket signature, so a bucket costs one launch instead of one per
  parameter. Callers dispatch each bucket as soon as it fills; JAX async
  dispatch then overlaps bucket N's collective with bucket N+1's pack and
  whatever backward work is still queued.
* `reassociate_bucketed` is the trace-time variant for the jitted train-step
  paths (`gluon.FusedTrainStep` / `parallel.ShardedTrainStep` `bucket_mb`
  knob): a concat/split identity that hands XLA one fused flat tensor per
  bucket, so cross-replica grad reductions combine bucket-wise instead of
  per-leaf.
* `BucketLayout`/`BucketSpec` freeze a bucketing run into a PERSISTENT,
  checkpointable bucket→key layout — the unit of ZeRO-1 weight-update
  sharding (`optimizer.zero`): each bucket is the reduce-scatter segment,
  its flat size padded to a world-size multiple so every rank owns one
  contiguous equal shard (`pack_flat`/`unpack_flat` are the jitted
  concat+pad / split inverses).

Telemetry: every flushed bucket counts `comm.bucket.count`,
`comm.bucket.bytes` and `comm.bucket.flush_reason.<reason>`; empty grads
count `comm.bucket.skipped`. Comm call sites record per-bucket
`comm.bucket` spans (cat `comm`) so the overlap is visible in
`mx.telemetry.dump_trace()` chrome dumps, and count `comm.collectives`
per launched comm program (per key on the unbucketed path) — the
collectives-per-step number the bench reports.

`bulk()` / `set_bulk_size()` remain the reference-compatible scope API.
"""
from __future__ import annotations

import contextlib
import os

import numpy as _np

import jax
import jax.numpy as jnp

__all__ = ["bulk", "set_bulk_size", "DEFAULT_BUCKET_MB", "bucket_bytes",
           "set_bucket_mb", "bucket_mb_scope", "Bucket", "GradBucketer",
           "bucketize", "fused_bucket_fn", "pack_bucket", "unpack_bucket",
           "reassociate_bucketed", "BucketSpec", "BucketLayout",
           "pack_flat", "unpack_flat", "SPAN_CAT_COMM", "comm_span_name",
           "SparseBucket", "SparseGradBucketer"]

_BULK_SIZE = 15  # the reference default (MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN)

# the comm trace-span vocabulary: every launched comm program records ONE
# span under cat `comm` with one of these name shapes, which is the whole
# contract telemetry.attribution's overlap profiler needs (no per-site
# instrumentation beyond the span itself)
SPAN_CAT_COMM = "comm"


def comm_span_name(key_range, kind="bucket"):
    """`comm.<kind>[<key-range>]` — bucket launches use kind="bucket",
    the per-key escape hatch "key", ZeRO's scatter/gather legs "rs"/"ag"."""
    return "comm.%s[%s]" % (kind, key_range)


def set_bulk_size(size):
    """reference: engine.set_bulk_size — returns the previous size."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """reference: engine.bulk — scope with a different bulk size."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


# ---------------------------------------------------------------------------
# bucket-size policy
# ---------------------------------------------------------------------------
DEFAULT_BUCKET_MB = 25.0

# process-wide override (set_bucket_mb / bucket_mb_scope); None -> env
_BUCKET_MB_OVERRIDE = None


def set_bucket_mb(mb):
    """Override the comm bucket cap (megabytes; 0 disables bucketing,
    None returns control to `MXNET_TPU_COMM_BUCKET_MB`). Returns the
    previous override so callers can restore it."""
    global _BUCKET_MB_OVERRIDE
    prev = _BUCKET_MB_OVERRIDE
    _BUCKET_MB_OVERRIDE = None if mb is None else float(mb)
    return prev


@contextlib.contextmanager
def bucket_mb_scope(mb):
    """Scope with a different comm bucket cap — the test/bench knob."""
    prev = set_bucket_mb(mb)
    try:
        yield
    finally:
        set_bucket_mb(prev)


def bucket_bytes(bucket_mb=None):
    """Effective bucket cap in BYTES; 0 means bucketing is disabled (the
    per-parameter escape hatch). Precedence: explicit `bucket_mb` arg >
    `set_bucket_mb`/`bucket_mb_scope` override > `MXNET_TPU_COMM_BUCKET_MB`
    env (default 25)."""
    mb = bucket_mb
    if mb is None:
        mb = _BUCKET_MB_OVERRIDE
    if mb is None:
        try:
            mb = float(os.environ.get("MXNET_TPU_COMM_BUCKET_MB",
                                      DEFAULT_BUCKET_MB))
        except (TypeError, ValueError):
            mb = DEFAULT_BUCKET_MB
    mb = float(mb)
    if mb <= 0:
        return 0
    return int(mb * 1024 * 1024)


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------
class Bucket:
    """One flat comm unit: ordered (key, array) pairs of a single dtype."""

    __slots__ = ("keys", "raws", "shapes", "dtype", "nbytes", "reason")

    def __init__(self, items, reason):
        self.keys = [k for k, _ in items]
        self.raws = [r for _, r in items]
        self.shapes = [tuple(r.shape) for r in self.raws]
        self.dtype = _np.dtype(self.raws[0].dtype)
        self.nbytes = sum(_nbytes(r) for r in self.raws)
        self.reason = reason

    def __len__(self):
        return len(self.keys)

    def key_range(self):
        """Compact key span for error/span context ("k0..kN" or "k0")."""
        if len(self.keys) == 1:
            return str(self.keys[0])
        return "%s..%s" % (self.keys[0], self.keys[-1])

    def span_name(self):
        """The canonical trace-span name every comm call site records for
        this bucket's launch (`comm.bucket[k0..kN]`, cat ``comm``) — ONE
        spelling, so `telemetry.attribution` and `parse_log --overlap`
        match launches without per-call-site knowledge."""
        return comm_span_name(self.key_range())

    def __repr__(self):
        return ("Bucket(keys=[%s], %d arrays, %d bytes, %s, reason=%s)"
                % (self.key_range(), len(self), self.nbytes, self.dtype,
                   self.reason))


def _nbytes(raw):
    return int(raw.size) * _np.dtype(raw.dtype).itemsize


class GradBucketer:
    """Greedy size-capped packer. Feed gradients with `add` in the order
    collectives should launch (the trainer feeds reverse-registration
    order, approximating backward completion order); each call returns the
    buckets that just became ready so the caller can dispatch them
    immediately — overlap comes from launching bucket N's comm before
    bucket N+1 is even packed.

    Flush reasons (counted under `comm.bucket.flush_reason.*`):
      full        adding the next grad would cross the cap
      dtype_split buckets are single-dtype; the next grad's dtype differs
      oversize    a single grad at/above the cap travels alone
      final       end-of-grads flush of the last partial bucket
    """

    def __init__(self, cap_bytes=None):
        self.cap = bucket_bytes() if cap_bytes is None else int(cap_bytes)
        self._open = []
        self._open_bytes = 0
        self._dtype = None

    def add(self, key, raw):
        """Queue one gradient; returns the list of buckets (possibly empty)
        that are now ready to launch. Empty/None grads are skipped (stale
        grads a `grad_req` change left behind)."""
        from .. import telemetry as _telem
        ready = []
        if raw is None or int(raw.size) == 0:
            _telem.inc("comm.bucket.skipped")
            return ready
        dt = _np.dtype(raw.dtype)
        nbytes = _nbytes(raw)
        if self._open and dt != self._dtype:
            ready.append(self._flush("dtype_split"))
        if self.cap and nbytes >= self.cap:
            # at/above the cap: never merged, never split — its own bucket
            if self._open:
                ready.append(self._flush("full"))
            ready.append(_count_bucket(Bucket([(key, raw)], "oversize")))
            return ready
        if self._open and self.cap and self._open_bytes + nbytes > self.cap:
            ready.append(self._flush("full"))
        self._open.append((key, raw))
        self._open_bytes += nbytes
        self._dtype = dt
        return ready

    def flush(self, reason="final"):
        """Close the open bucket; returns it (or None if empty)."""
        if not self._open:
            return None
        return self._flush(reason)

    def _flush(self, reason):
        b = Bucket(self._open, reason)
        self._open = []
        self._open_bytes = 0
        self._dtype = None
        return _count_bucket(b)


def _count_bucket(bucket):
    from .. import telemetry as _telem
    if _telem.ENABLED:
        _telem.inc("comm.bucket.count")
        _telem.inc("comm.bucket.bytes", bucket.nbytes)
        _telem.inc("comm.bucket.flush_reason.%s" % bucket.reason)
    return bucket


def bucketize(entries, cap_bytes=None):
    """Pack an iterable of (key, raw_array) into a list of Buckets."""
    bucketer = GradBucketer(cap_bytes)
    out = []
    for key, raw in entries:
        out.extend(bucketer.add(key, raw))
    tail = bucketer.flush()
    if tail is not None:
        out.append(tail)
    return out


# ---------------------------------------------------------------------------
# sparse (row_sparse) buckets: the comm unit is a set of keys' (row-id,
# row-values) pairs, sized by TOUCHED bytes — a giant table whose push only
# touches a few thousand rows packs beside its neighbors, where the dense
# bucketer would count full-table bytes (ISSUE 17 tentpole part 3)
# ---------------------------------------------------------------------------
class SparseBucket:
    """One sparse comm unit: ordered (key, ids, vals) triples of a single
    row dtype. `nbytes` counts the wire payload (ids + touched rows),
    NOT the dense table bytes."""

    __slots__ = ("keys", "ids", "vals", "dtype", "nbytes", "reason")

    def __init__(self, items, reason):
        self.keys = [k for k, _, _ in items]
        self.ids = [i for _, i, _ in items]
        self.vals = [v for _, _, v in items]
        self.dtype = _np.dtype(self.vals[0].dtype)
        self.nbytes = sum(_sparse_nbytes(i, v)
                          for i, v in zip(self.ids, self.vals))
        self.reason = reason

    def __len__(self):
        return len(self.keys)

    def key_range(self):
        if len(self.keys) == 1:
            return str(self.keys[0])
        return "%s..%s" % (self.keys[0], self.keys[-1])

    def span_name(self):
        return comm_span_name(self.key_range(), kind="sparse")

    def __repr__(self):
        return ("SparseBucket(keys=[%s], %d keys, %d bytes, %s, reason=%s)"
                % (self.key_range(), len(self), self.nbytes, self.dtype,
                   self.reason))


def _sparse_nbytes(ids, vals):
    return (int(ids.size) * _np.dtype(ids.dtype).itemsize
            + int(vals.size) * _np.dtype(vals.dtype).itemsize)


class SparseGradBucketer:
    """Greedy size-capped packer over (key, ids, vals) sparse pushes —
    `GradBucketer` with touched-row byte accounting. Flush reasons match
    the dense packer's and count under
    ``comm.sparse.bucket.flush_reason.*``."""

    def __init__(self, cap_bytes=None):
        self.cap = bucket_bytes() if cap_bytes is None else int(cap_bytes)
        self._open = []
        self._open_bytes = 0
        self._dtype = None

    def add(self, key, ids, vals):
        from .. import telemetry as _telem
        ready = []
        if vals is None or int(vals.size) == 0:
            _telem.inc("comm.sparse.bucket.skipped")
            return ready
        dt = _np.dtype(vals.dtype)
        nbytes = _sparse_nbytes(ids, vals)
        if self._open and dt != self._dtype:
            ready.append(self._flush("dtype_split"))
        if self.cap and nbytes >= self.cap:
            if self._open:
                ready.append(self._flush("full"))
            ready.append(_count_sparse_bucket(
                SparseBucket([(key, ids, vals)], "oversize")))
            return ready
        if self._open and self.cap and self._open_bytes + nbytes > self.cap:
            ready.append(self._flush("full"))
        self._open.append((key, ids, vals))
        self._open_bytes += nbytes
        self._dtype = dt
        return ready

    def flush(self, reason="final"):
        if not self._open:
            return None
        return self._flush(reason)

    def _flush(self, reason):
        b = SparseBucket(self._open, reason)
        self._open = []
        self._open_bytes = 0
        self._dtype = None
        return _count_sparse_bucket(b)


def _count_sparse_bucket(bucket):
    from .. import telemetry as _telem
    if _telem.ENABLED:
        _telem.inc("comm.sparse.bucket.count")
        _telem.inc("comm.sparse.bucket.bytes", bucket.nbytes)
        _telem.inc("comm.sparse.bucket.flush_reason.%s" % bucket.reason)
    return bucket


# ---------------------------------------------------------------------------
# fused flatten -> comm -> unflatten programs
# ---------------------------------------------------------------------------
# (tag, n_slots, shapes, dtype) -> jitted program. `tag` names the comm_fn
# BEHAVIOR (jax.jit caches by callable identity; the first comm_fn seen for a
# tag+signature is baked into the cached program) — callers must use a
# distinct tag per distinct comm semantics.
_FUSED_CACHE = {}


def _split_points(shapes):
    sizes = [int(_np.prod(s, dtype=_np.int64)) for s in shapes]
    return sizes, list(_np.cumsum(sizes)[:-1])


def fused_bucket_fn(tag, comm_fn, shapes, dtype, n_slots=1,
                    with_finite=False):
    """Compile (and cache) ONE program: flatten `n_slots` groups of arrays
    with these shapes, run ``comm_fn(*flats)`` (flat vector per slot ->
    one flat vector), and unflatten back to `shapes`. This is the bucket's
    single launch — XLA fuses pack, comm, and scatter.

    with_finite=True appends ONE extra output: a scalar bool, True iff the
    post-comm flat vector is all-finite — the integrity sentinel's bucket
    check, fused into the launch the collective already pays for (a
    non-finite input propagates through any sum/identity comm_fn, so
    checking the output covers both legs). Cached separately from the
    plain program, so toggling MXNET_TPU_INTEGRITY never poisons a warm
    cache."""
    key = (tag, int(n_slots), tuple(tuple(s) for s in shapes), str(dtype),
           bool(with_finite))
    fn = _FUSED_CACHE.get(key)
    if fn is not None:
        return fn
    nshapes = len(shapes)
    _, splits = _split_points(shapes)

    def run(*raws):
        flats = []
        for s in range(n_slots):
            grp = raws[s * nshapes:(s + 1) * nshapes]
            flats.append(jnp.concatenate([r.reshape(-1) for r in grp])
                         if nshapes > 1 else grp[0].reshape(-1))
        out = comm_fn(*flats)
        parts = jnp.split(out, splits) if splits else [out]
        shaped = tuple(p.reshape(sh) for p, sh in zip(parts, shapes))
        if with_finite:
            return shaped + (jnp.isfinite(out).all(),)
        return shaped

    fn = jax.jit(run)
    _FUSED_CACHE[key] = fn
    return fn


def _identity(flat):
    return flat


def pack_bucket(bucket):
    """One jitted concat of the bucket's raveled arrays -> flat vector.
    For comm that cannot run inside jit (cross-process exchanges) the
    flow is pack_bucket -> exchange -> unpack_bucket: 2 launches per
    bucket instead of 2 per parameter."""
    if len(bucket.raws) == 1:
        return bucket.raws[0].reshape(-1)
    key = ("pack", tuple(tuple(s) for s in bucket.shapes), str(bucket.dtype))
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda *rs: jnp.concatenate(
            [r.reshape(-1) for r in rs]))
        _FUSED_CACHE[key] = fn
    return fn(*bucket.raws)


def unpack_bucket(bucket, flat):
    """One jitted split of a flat vector back to the bucket's shapes."""
    return fused_bucket_fn("unpack", _identity, bucket.shapes,
                           bucket.dtype)(flat)


# ---------------------------------------------------------------------------
# persistent bucket layout — the unit of ZeRO weight-update sharding
# ---------------------------------------------------------------------------
class BucketSpec:
    """One bucket of a frozen `BucketLayout`: the static shape of a comm
    segment (no array payloads). `padded` is the flat element count rounded
    up to the next multiple of the layout's world size, so the bucket
    reduce-scatters into `world` equal contiguous shards of `shard`
    elements each (the zero-fill rides inside the fused pack program, the
    same trick as `all_reduce_multi`'s odd-leading-dim padding)."""

    __slots__ = ("index", "keys", "shapes", "dtype", "sizes", "size",
                 "padded", "shard")

    def __init__(self, index, keys, shapes, dtype, world):
        self.index = int(index)
        self.keys = [str(k) for k in keys]
        self.shapes = [tuple(int(d) for d in s) for s in shapes]
        self.dtype = _np.dtype(dtype)
        self.sizes = [int(_np.prod(s, dtype=_np.int64)) for s in self.shapes]
        self.size = int(sum(self.sizes))
        world = max(1, int(world))
        self.padded = (self.size + world - 1) // world * world
        self.shard = self.padded // world

    def __len__(self):
        return len(self.keys)

    def key_range(self):
        if len(self.keys) == 1:
            return str(self.keys[0])
        return "%s..%s" % (self.keys[0], self.keys[-1])

    def span_name(self, kind="bucket"):
        """Canonical comm span name for this bucket's launches (ZeRO's
        reduce-scatter / all-gather legs pass kind="rs"/"ag")."""
        return comm_span_name(self.key_range(), kind)

    def nbytes(self):
        return self.size * self.dtype.itemsize

    def shard_nbytes(self):
        return self.shard * self.dtype.itemsize

    def segments(self):
        """[(key, offset, size, shape)] over the unpadded flat vector."""
        out, off = [], 0
        for k, n, s in zip(self.keys, self.sizes, self.shapes):
            out.append((k, off, n, s))
            off += n
        return out

    def shard_segments(self, rank):
        """The pieces of `rank`'s shard, as (key, start_in_shard,
        length, start_in_key) — the map a per-parameter quantity (lr/wd
        multipliers) needs to land on the owned flat shard. Padding tail
        elements belong to no key and are simply absent."""
        lo, hi = rank * self.shard, (rank + 1) * self.shard
        out = []
        for k, off, n, _ in self.segments():
            s, e = max(off, lo), min(off + n, hi)
            if s < e:
                out.append((k, s - lo, e - s, s - off))
        return out

    def __repr__(self):
        return ("BucketSpec(#%d keys=[%s] %d elems pad=%d shard=%d %s)"
                % (self.index, self.key_range(), self.size, self.padded,
                   self.shard, self.dtype))


class BucketLayout:
    """Persistent bucket→key layout: frozen after the first flush,
    checkpointable, the contract between gradient reduce-scatter, the
    sharded optimizer state, and the weight all-gather. Once frozen the
    SAME layout must describe every subsequent step — owned shards,
    per-bucket residuals, and checkpoints all key on bucket indices, so a
    drifting membership would silently corrupt state. `assert_matches`
    enforces that."""

    VERSION = 1

    def __init__(self, buckets, world):
        self.world = max(1, int(world))
        self.buckets = list(buckets)
        # HBM ledger: the frozen layout IS the flat-gradient working set
        # this rank materializes every step (pack + reduce-scatter input)
        from ..telemetry import ledger as _ledger
        _ledger.account("grad_buckets", self.total_nbytes())

    @classmethod
    def from_entries(cls, entries, world, cap_bytes=None):
        """Freeze a layout from (key, array) pairs by running them through
        the standard `GradBucketer` packing (same caps, same dtype splits,
        same oversize rules as the allreduce path)."""
        buckets = []
        for i, b in enumerate(bucketize(entries, cap_bytes)):
            buckets.append(BucketSpec(i, b.keys, b.shapes, b.dtype, world))
        return cls(buckets, world)

    def __len__(self):
        return len(self.buckets)

    def __iter__(self):
        return iter(self.buckets)

    def keys(self):
        out = []
        for b in self.buckets:
            out.extend(b.keys)
        return out

    def assert_matches(self, keys):
        """The frozen-layout guard: every step after the first must feed
        the exact key sequence the layout was frozen from."""
        keys = [str(k) for k in keys]
        if keys != self.keys():
            raise ValueError(
                "bucket layout is frozen: step fed keys %s but the layout "
                "holds %s — a changed parameter set needs a new layout "
                "(and fresh sharded optimizer state)" % (keys, self.keys()))

    def total_nbytes(self):
        return sum(b.nbytes() for b in self.buckets)

    def to_payload(self):
        """JSON-able dict — checkpointed next to the sharded state so a
        restore (possibly onto a different world size) can re-derive every
        shard boundary without replaying a bucketing pass."""
        return {
            "version": self.VERSION,
            "world": self.world,
            "buckets": [{"keys": list(b.keys),
                         "shapes": [list(s) for s in b.shapes],
                         "dtype": str(b.dtype)} for b in self.buckets],
        }

    @classmethod
    def from_payload(cls, payload, world=None):
        """Rebuild from `to_payload` output; `world` overrides the saved
        world size (the elastic-restore path: same buckets, new shard
        boundaries)."""
        if int(payload.get("version", -1)) != cls.VERSION:
            raise ValueError("unsupported bucket-layout payload version %r"
                             % (payload.get("version"),))
        world = payload["world"] if world is None else world
        buckets = [BucketSpec(i, b["keys"], b["shapes"], b["dtype"], world)
                   for i, b in enumerate(payload["buckets"])]
        return cls(buckets, world)

    def rebuild_for_world(self, world):
        """Same buckets, re-partitioned for a different world size — the
        elastic shrink/grow primitive."""
        return BucketLayout.from_payload(self.to_payload(), world=world)

    def __repr__(self):
        return ("BucketLayout(%d buckets, %d keys, world=%d, %dB)"
                % (len(self.buckets), len(self.keys()), self.world,
                   self.total_nbytes()))


def pack_flat(spec, raws):
    """ONE jitted concat(+zero-pad to `spec.padded`) of a bucket's raveled
    arrays — the reduce-scatter-ready flat vector. Traceable: also usable
    inside shard_map'd code (the cache key is static)."""
    key = ("pack_pad", tuple(spec.shapes), str(spec.dtype), spec.padded)
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        pad = spec.padded - spec.size
        dtype = jnp.dtype(spec.dtype)

        def run(*rs):
            parts = [r.reshape(-1) for r in rs]
            if pad:
                parts.append(jnp.zeros((pad,), dtype))
            return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

        fn = jax.jit(run)
        _FUSED_CACHE[key] = fn
    return fn(*raws)


def unpack_flat(spec, flat):
    """ONE jitted split of a padded flat vector back to the bucket's
    shapes (the padding tail is dropped)."""
    key = ("unpack_pad", tuple(spec.shapes), str(spec.dtype), spec.padded)
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        splits = list(_np.cumsum(spec.sizes)[:-1])
        shapes = spec.shapes

        def run(f):
            f = f[:sum(spec.sizes)]
            parts = jnp.split(f, splits) if splits else [f]
            return tuple(p.reshape(s) for p, s in zip(parts, shapes))

        fn = jax.jit(run)
        _FUSED_CACHE[key] = fn
    return fn(flat)


def reassociate_bucketed(raws, bucket_mb=None):
    """Trace-time regrouping for the jitted train-step paths: concat `raws`
    into size-capped flat buckets and split back. Numerically this is the
    identity (no arithmetic — bit-exact), but the lowered program carries
    one fused flat tensor per bucket, so XLA's collective scheduling
    combines the cross-replica grad reductions bucket-wise instead of
    emitting one small all-reduce per leaf. Under jit the bucket telemetry
    counts once per (re)trace — buckets-per-program, not per step."""
    cap = bucket_bytes(bucket_mb)
    if not cap or len(raws) < 2:
        return list(raws)
    out = list(raws)
    for bucket in bucketize(enumerate(raws), cap):
        if len(bucket) == 1:
            continue  # nothing to fuse for a lone oversize grad
        _, splits = _split_points(bucket.shapes)
        flat = jnp.concatenate([r.reshape(-1) for r in bucket.raws])
        parts = jnp.split(flat, splits)
        for idx, part, shape in zip(bucket.keys, parts, bucket.shapes):
            out[idx] = part.reshape(shape)
    return out


# readiness-ordered flushing + schedule autotuning (ISSUE 19) live in
# submodules; re-exported here so `from .. import engine` callers see one
# flat engine namespace
from .ready import ReadyScheduler            # noqa: E402
from . import autotune                       # noqa: E402
from .autotune import (                      # noqa: E402
    CommSchedule, ScheduleAutotuner, current_schedule, set_schedule,
    schedule_payload, restore_schedule, autotune_enabled)

__all__ += ["ReadyScheduler", "autotune", "CommSchedule",
            "ScheduleAutotuner", "current_schedule", "set_schedule",
            "schedule_payload", "restore_schedule", "autotune_enabled"]
