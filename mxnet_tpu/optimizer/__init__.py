"""Optimizer API. reference: python/mxnet/optimizer/__init__.py."""
from . import optimizer
from .optimizer import *  # noqa: F401,F403
from .optimizer import Optimizer, create, register, get_updater, Updater
from .zero import ZeroComm, ZeroUpdater, get_zero_updater, zero_enabled

__all__ = optimizer.__all__ + ["ZeroComm", "ZeroUpdater",
                               "get_zero_updater", "zero_enabled"]
