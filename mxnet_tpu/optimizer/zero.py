"""ZeRO-1 weight-update sharding over the bucketed comm engine.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md) applied to the kvstore/Trainer path: instead of
every rank allreducing whole gradients and running an identical
(replicated) optimizer step, each step becomes

    reduce-scatter(grads, per bucket)          — each rank receives ONE
                                                 contiguous shard of each
                                                 bucket's gradient sum
    fused flat shard update (owned shard only) — optimizer state exists
                                                 ONLY for owned shards, so
                                                 Adam memory divides by the
                                                 world size
    all-gather(updated weights, per bucket)    — full weights return to
                                                 every rank for the next
                                                 forward

The unit of sharding is the PR 4 comm bucket: a persistent
`mx.engine.BucketLayout` — frozen from the first step's gradient flush,
checkpointable — makes each bucket the reduce-scatter segment, and its
`BucketSpec` padding (flat size rounded to a world-size multiple) keeps
every shard equal-sized. The shard update itself is ONE fused XLA dispatch
per dtype-bucket (`optimizer._fused_flat_fn`: a single pass over
params+grads+momentum instead of three — the "Tensor Processing
Primitives" shape), with per-element lr/wd vectors carrying per-parameter
lr_mult/wd_mult and Adam bias correction through the flattening.

Comm is injectable (`ZeroComm`): the default world-1 backend is the
identity (the protocol costs nothing off-pod), `kvstore_dist` supplies a
cross-worker backend over the worker mesh, and tests drive a simulated
fleet on one process (the `CommitCoordinator` fake-gather pattern —
CPU tier-1 cannot run multiprocess collectives).

Telemetry: `comm.reduce_scatter` / `comm.all_gather` count launched
collectives (plus `comm.collectives` so existing per-step accounting
holds), the `opt.state_bytes_per_rank` gauge measures the sharded
optimizer-state footprint, and every fused shard update observes the
`opt.fused_update_ms` histogram.
"""
from __future__ import annotations

import pickle
import time
import warnings

import numpy as _np
import jax.numpy as jnp

from .. import engine as _engine
from ..ndarray import NDArray
from .optimizer import Adam, LAMB, Optimizer, SGD, _fused_flat_fn

__all__ = ["ZeroComm", "ZeroUpdater", "get_zero_updater", "zero_enabled"]


def zero_enabled(flag=None):
    """Resolve the ZeRO opt-in: an explicit flag wins, else the
    `MXNET_TPU_ZERO` env var (default off)."""
    if flag is not None:
        return bool(flag)
    import os
    return os.environ.get("MXNET_TPU_ZERO", "0").lower() in (
        "1", "true", "yes", "on")


class ZeroComm:
    """Collective backend contract for the ZeRO path — and its world-1
    implementation, where both exchanges are the identity (one rank owns
    every shard; the fused update and sharded-state bookkeeping still run,
    so the machinery is exercised and checkpoints are world-portable).

    reduce_scatter(spec, flat): this rank's (spec.shard,) slice of the
        cross-rank SUM of each rank's (spec.padded,) flat contribution.
    all_gather(spec, shard): the full (spec.padded,) vector reassembled
        from every rank's shard.
    all_reduce(spec, value): the cross-rank SUM of a small per-bucket
        vector — the LAMB per-segment squared norms, whose segments can
        straddle shard boundaries, are completed through this (a default
        implementation keeps pre-ISSUE-10 custom comms working).
    """

    world = 1
    rank = 0

    def reduce_scatter(self, spec, flat):
        return flat

    def all_gather(self, spec, shard):
        return shard

    def all_reduce(self, spec, value):
        return value


class ZeroUpdater:
    """The sharded analog of `optimizer.Updater`: applied once per step to
    the FULL key set (ZeRO owns the whole bucket layout; partial updates
    would desync owned shards), serializable via `get_states`/`set_states`
    like the Updater the reference ships to parameter servers — the
    payload carries the frozen bucket layout plus the all-gathered full
    optimizer state, so a restore re-partitions onto ANY world size
    (elastic shrink/grow) bit-preserving.

    Only SGD (incl. momentum), Adam, and LAMB run here — they are the
    optimizers with fused flat kernels (LAMB's per-segment norm reduction
    landed with ISSUE 10); others raise at construction rather than
    silently falling back to a replicated update.
    """

    def __init__(self, optimizer, comm=None, cap_bytes=None):
        if not isinstance(optimizer, Optimizer):
            raise TypeError("ZeroUpdater needs an Optimizer instance, got %s"
                            % type(optimizer))
        if type(optimizer) is SGD:
            self._kind = "sgd"
        elif type(optimizer) is Adam:
            self._kind = "adam"
        elif type(optimizer) is LAMB:
            self._kind = "lamb"
        else:
            raise ValueError(
                "ZeRO sharded update supports exactly SGD, Adam and LAMB "
                "(the fused flat kernels); got %s — disable zero or switch "
                "optimizer" % type(optimizer).__name__)
        self.optimizer = optimizer
        self.comm = comm if comm is not None else ZeroComm()
        self._cap_bytes = cap_bytes
        self.layout = None
        self._w_shards = {}       # bucket index -> owned weight shard
        self._masters = {}        # bucket index -> fp32 master shard (mp)
        self._states = {}         # bucket index -> {slot: flat shard}
        self._mult_cache = {}     # bucket index -> (scalars, lr_vec, wd_vec)
        self._seg_cache = {}      # bucket index -> (segments, seg_ids, K)
        self.aggregate_updates = True

    # -- layout / state allocation --------------------------------------
    @property
    def _slots(self):
        return ("mom",) if self._kind == "sgd" else ("mean", "var")

    def _bucket_mp(self, spec):
        return (self.optimizer.multi_precision
                and spec.dtype == _np.float16)

    def _freeze(self, keys, grads):
        cap = (_engine.bucket_bytes() if self._cap_bytes is None
               else self._cap_bytes)
        self.layout = _engine.BucketLayout.from_entries(
            zip(keys, grads), self.comm.world, cap)

    def _ensure_shards(self, spec, weights_by_key):
        """Own-shard weight slice + lazily-allocated state shards. Weights
        are sliced from the CURRENT full store values, so a restore that
        rewrote the store (checkpoint load) re-seeds shards exactly."""
        b = spec.index
        if b in self._w_shards:
            return
        raws = [weights_by_key[k]._read().astype(spec.dtype)
                for k in spec.keys]
        flat = _engine.pack_flat(spec, raws)
        lo = self.comm.rank * spec.shard
        self._w_shards[b] = flat[lo:lo + spec.shard]
        mp = self._bucket_mp(spec)
        if mp:
            # keep a restored master: a checkpointed fp32 master carries
            # precision the fp16 store weights lost — re-deriving it
            # here would break bit-preserving restore
            if b not in self._masters:
                self._masters[b] = self._w_shards[b].astype(jnp.float32)
        elif spec.dtype == _np.float16:
            warnings.warn("Accumulating with float16 in optimizer can lead "
                          "to poor accuracy or slow convergence. Consider "
                          "using multi_precision=True option of the "
                          "optimizer")
        if b not in self._states:
            state_dtype = jnp.float32 if mp else jnp.dtype(spec.dtype)
            if self._kind == "sgd" and self.optimizer.momentum == 0.0:
                self._states[b] = {}
            else:
                self._states[b] = {s: jnp.zeros((spec.shard,), state_dtype)
                                   for s in self._slots}
        self._update_state_gauge()

    def state_bytes_per_rank(self):
        """Owned optimizer-state bytes on THIS rank (momentum/moments plus
        any fp32 masters) — what the `opt.state_bytes_per_rank` gauge
        reports; divide the replicated total by the world size and you
        should land here (padding adds at most world-1 elements/bucket)."""
        total = 0
        for st in self._states.values():
            total += sum(int(a.size) * a.dtype.itemsize for a in st.values())
        for m in self._masters.values():
            total += int(m.size) * m.dtype.itemsize
        return total

    def _update_state_gauge(self):
        from .. import telemetry as _telem
        from ..telemetry import ledger as _ledger
        nbytes = self.state_bytes_per_rank()
        _telem.set_gauge("opt.state_bytes_per_rank", nbytes)
        _ledger.account("optimizer", nbytes)

    # -- per-step scalars ------------------------------------------------
    def _idx(self, key):
        return int(key) if str(key).isdigit() else str(key)

    def _lr_wd_vectors(self, spec):
        """Per-ELEMENT lr/wd vectors for this rank's shard: each owned
        segment is filled with its parameter's scalar lr/wd (scheduler,
        lr_mult/wd_mult, and Adam bias correction already folded in — the
        exact scalars the replicated per-parameter path would use);
        padding elements stay 0. Each vector caches on its own scalar
        tuple: wd_vec virtually always hits, and lr_vec hits whenever the
        folded lr scalars repeat (constant-lr SGD every step; under Adam
        the bias-correction factor moves each step, and it MUST fold in
        host double precision — the replicated op path does — so the
        lr_vec rebuild there is the price of bit parity)."""
        opt = self.optimizer
        indices = [self._idx(k) for k in spec.keys]
        lrs = opt._get_lrs(indices)
        wds = opt._get_wds(indices)
        if self._kind == "adam":
            import math
            for i, idx in enumerate(indices):
                t = opt._index_update_count[idx]
                lrs[i] *= math.sqrt(1. - opt.beta2 ** t) / \
                    (1. - opt.beta1 ** t)
        cache = self._mult_cache.setdefault(spec.index, {})

        def vec(slot, scalars):
            sig = tuple(scalars)
            hit = cache.get(slot)
            if hit is not None and hit[0] == sig:
                return hit[1]
            by_key = dict(zip(spec.keys, scalars))
            out = _np.zeros((spec.shard,), _np.float32)
            for k, start, length, _ in spec.shard_segments(self.comm.rank):
                out[start:start + length] = by_key[k]
            dev = jnp.asarray(out)
            cache[slot] = (sig, dev)
            return dev

        return vec("lr", lrs), vec("wd", wds)

    # -- the step --------------------------------------------------------
    def __call__(self, index, grad, weight):
        """Updater-protocol entry: the kvstore/Trainer hand the FULL key
        set in one aggregated call."""
        if not isinstance(index, (list, tuple)):
            index, grad, weight = [index], [grad], [weight]
        self.step(list(index), [g._read() if isinstance(g, NDArray) else g
                                for g in grad], list(weight))

    def step(self, keys, grads, weights):
        """One sharded update: `grads` are this rank's locally-merged raw
        gradient arrays, `weights` the full parameter NDArrays (written in
        place with the all-gathered result)."""
        from .. import telemetry as _telem
        from ..resilience import faults as _faults
        from ..resilience.retry import call_with_retry
        keys = [str(k) for k in keys]
        # zero-size grads never enter a bucket (GradBucketer skips them);
        # filter them HERE too so the frozen layout and every later step
        # agree on the key sequence — an empty parameter has nothing to
        # update anyway
        kept = [i for i, g in enumerate(grads) if int(g.size)]
        if len(kept) != len(keys):
            keys = [keys[i] for i in kept]
            grads = [grads[i] for i in kept]
            weights = [weights[i] for i in kept]
        if self.layout is None:
            self._freeze(keys, grads)
        else:
            self.layout.assert_matches(keys)
        grads_by_key = dict(zip(keys, grads))
        weights_by_key = dict(zip(keys, weights))
        opt = self.optimizer
        for idx in (self._idx(k) for k in keys):
            opt._update_count(idx)
        clip = opt.clip_gradient
        # software pipeline: bucket N's reduce-scatter launches BEFORE
        # bucket N-1's all-gather-back, so under async dispatch the two
        # collectives overlap instead of serializing around the update
        # (ISSUE 19; "Automatic Cross-Replica Sharding of Weight Update").
        # Per-bucket arithmetic and write order are untouched — bit parity
        # with the sequential loop holds by construction.
        pending = None   # (spec, new_w) awaiting its all-gather
        for spec in self.layout:
            self._ensure_shards(spec, weights_by_key)
            flat_g = _engine.pack_flat(
                spec, [grads_by_key[k] for k in spec.keys])
            self._guard_bucket(spec, flat_g)
            g_shard = self._scatter_leg(spec, flat_g)
            if pending is not None:
                _telem.inc("comm.zero.pipelined")
                self._gather_writeback(pending[0], pending[1],
                                       weights_by_key)
            pending = (spec, self._fused_shard_update(spec, g_shard, clip))
        if pending is not None:
            self._gather_writeback(pending[0], pending[1], weights_by_key)
        # re-assert every step: gauges are cheap and `telemetry.reset()`
        # between measurement windows must not lose the footprint
        self._update_state_gauge()

    def _guard_bucket(self, spec, flat_g):
        """Integrity sentinel over one packed ZeRO bucket
        (MXNET_TPU_INTEGRITY=1): the bucket is already ONE flat array, so
        the all-finite check is a single fused reduction — it trips BEFORE
        the reduce-scatter launches, so no shard update ever sees the
        poisoned values."""
        from ..resilience import integrity as _integrity
        if _integrity.enabled():
            _integrity.check_finite([flat_g], site="zero.bucket",
                                    keys=spec.keys)

    def _scatter_leg(self, spec, flat_g):
        """The reduce-scatter leg for one bucket: fault site, counters,
        span, retry. Safe to launch while backward is still running (it
        only reads immutable grad arrays) — the readiness push path calls
        it per completed bucket, out of bucket-index order."""
        from .. import telemetry as _telem
        from ..resilience import faults as _faults
        from ..resilience.retry import call_with_retry
        context = "bucket=[%s] %dB world=%d" % (
            spec.key_range(), spec.nbytes(), self.comm.world)

        def scatter(flat_g=flat_g, spec=spec, context=context):
            _faults.check("collective.reduce_scatter", context=context)
            return self.comm.reduce_scatter(spec, flat_g)

        _telem.inc("comm.collectives")
        _telem.inc("comm.reduce_scatter")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        g_shard = call_with_retry(
            scatter, site="collective.reduce_scatter", context=context)
        _telem.record_span(spec.span_name("rs"), _engine.SPAN_CAT_COMM,
                           ts, time.perf_counter() - t0)
        return g_shard

    def _gather_writeback(self, spec, new_w, weights_by_key):
        """The all-gather-back leg: retried exchange, then per-key store
        writes of the reassembled full weights."""
        from .. import telemetry as _telem
        from ..resilience import faults as _faults
        from ..resilience.retry import call_with_retry
        context = "bucket=[%s] %dB world=%d" % (
            spec.key_range(), spec.nbytes(), self.comm.world)

        def gather(new_w=new_w, spec=spec, context=context):
            _faults.check("collective.all_gather", context=context)
            return self.comm.all_gather(spec, new_w)

        _telem.inc("comm.collectives")
        _telem.inc("comm.all_gather")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        full = call_with_retry(
            gather, site="collective.all_gather", context=context)
        _telem.record_span(spec.span_name("ag"), _engine.SPAN_CAT_COMM,
                           ts, time.perf_counter() - t0)
        for k, part in zip(spec.keys, _engine.unpack_flat(spec, full)):
            stored = weights_by_key[k]
            stored._write(part.astype(stored.dtype))

    # -- readiness-ordered entry points (ISSUE 19) -----------------------
    def scatter_ready(self, spec, flat_g, weights_by_key):
        """Launch one completed bucket's reduce-scatter the moment its
        members finish backward (frozen-layout readiness mode). Returns
        the g_shard handle `finish_ready` consumes."""
        self._ensure_shards(spec, weights_by_key)
        self._guard_bucket(spec, flat_g)
        return self._scatter_leg(spec, flat_g)

    def finish_ready(self, arrivals, weights_by_key):
        """Complete a readiness round at step time: `arrivals` is
        [(spec, g_shard)] in COMPLETION order (any permutation of the
        layout). Per-bucket update + all-gather run in that order, each
        bucket's all-gather pipelined behind the next bucket's update —
        every reduce-scatter already launched during backward. The
        arithmetic per bucket is identical to `step`."""
        from .. import telemetry as _telem
        if self.layout is None:
            raise RuntimeError("finish_ready needs a frozen layout "
                               "(first step goes through step())")
        got = [s.index for s, _ in arrivals]
        want = [s.index for s in self.layout]
        if sorted(got) != sorted(want):
            raise ValueError(
                "readiness round arrived with buckets %s but the frozen "
                "layout holds %s" % (sorted(got), sorted(want)))
        opt = self.optimizer
        for k in self.layout.keys():
            opt._update_count(self._idx(k))
        clip = opt.clip_gradient
        pending = None
        for spec, g_shard in arrivals:
            if pending is not None:
                _telem.inc("comm.zero.pipelined")
                self._gather_writeback(pending[0], pending[1],
                                       weights_by_key)
            pending = (spec, self._fused_shard_update(spec, g_shard, clip))
        if pending is not None:
            self._gather_writeback(pending[0], pending[1], weights_by_key)
        self._update_state_gauge()

    def _fused_shard_update(self, spec, g_shard, clip):
        """ONE fused dispatch over the owned flat shard (per dtype-bucket,
        not per parameter)."""
        from .. import telemetry as _telem
        opt = self.optimizer
        b = spec.index
        mp = self._bucket_mp(spec)
        lr_vec, wd_vec = self._lr_wd_vectors(spec)
        w = self._w_shards[b]
        master = self._masters.get(b)
        rescale = jnp.float32(opt.rescale_grad)
        clip_v = jnp.float32(clip if clip is not None else 0.0)
        t0 = time.perf_counter()
        if self._kind == "sgd":
            momentum_on = opt.momentum != 0.0
            fn = _fused_flat_fn("sgd", momentum_on, clip is not None, mp)
            new_w, new_mom, new_master = fn(
                w, g_shard, self._states[b].get("mom"), master, lr_vec,
                wd_vec, jnp.float32(opt.momentum), rescale, clip_v)
            if momentum_on:
                self._states[b]["mom"] = new_mom
        elif self._kind == "adam":
            fn = _fused_flat_fn("adam", True, clip is not None, mp)
            new_w, new_mean, new_var, new_master = fn(
                w, g_shard, self._states[b]["mean"], self._states[b]["var"],
                master, lr_vec, wd_vec, jnp.float32(opt.beta1),
                jnp.float32(1.0 - opt.beta1), jnp.float32(opt.beta2),
                jnp.float32(1.0 - opt.beta2), jnp.float32(opt.epsilon),
                rescale, clip_v)
            self._states[b]["mean"] = new_mean
            self._states[b]["var"] = new_var
        else:
            new_w, new_master = self._lamb_shard_update(
                spec, g_shard, clip, mp, lr_vec, wd_vec, rescale, clip_v)
        self._w_shards[b] = new_w
        if mp:
            self._masters[b] = new_master
        _telem.observe("opt.fused_update_ms",
                       (time.perf_counter() - t0) * 1e3)
        return new_w

    def _seg_info(self, spec):
        """Static per-bucket segment metadata for LAMB's per-key norms:
        (segments tuple of (key_index, start, length) in THIS rank's
        shard, per-element key-index vector, n_keys)."""
        hit = self._seg_cache.get(spec.index)
        if hit is not None:
            return hit
        by_key = {k: i for i, k in enumerate(spec.keys)}
        segments = []
        ids = _np.zeros((spec.shard,), _np.int32)
        for k, start, length, _ in spec.shard_segments(self.comm.rank):
            segments.append((by_key[k], start, length))
            ids[start:start + length] = by_key[k]
        info = (tuple(segments), jnp.asarray(ids), len(spec.keys))
        self._seg_cache[spec.index] = info
        return info

    def _lamb_shard_update(self, spec, g_shard, clip, mp, lr_vec, wd_vec,
                           rescale, clip_v):
        """LAMB over the owned flat shard, the ISSUE 10 two-pass shape:
        pass 1 (moment update + raw direction + per-SEGMENT squared-norm
        partials in the same sweep), ONE tiny all-reduce to complete the
        per-parameter ‖w‖/‖g‖ norms across shard boundaries, pass 2
        (trust-ratio-scaled apply). Arithmetic per element matches the
        eager lamb_update_phase1/phase2 ops; the norm accumulation order
        differs from `jnp.linalg.norm`, so parity is fp32-round-off, not
        bitwise (documented in tests/test_zero.py)."""
        from .. import telemetry as _telem
        from ..ops import fused_optimizer as _fops
        from ..resilience import faults as _faults
        from ..resilience.retry import call_with_retry
        opt = self.optimizer
        b = spec.index
        segments, seg_ids, n_keys = self._seg_info(spec)
        w = self._w_shards[b]
        master = self._masters.get(b)
        # one shared update count per step (ZeroUpdater always steps the
        # full key set, so t is uniform across the bucket's keys)
        t = opt._index_update_count[self._idx(spec.keys[0])]
        fn1 = _fops.lamb_flat_phase1_fn(clip is not None, mp,
                                        bool(opt.bias_correction),
                                        segments, n_keys)
        # bias-correction complements in python double, like the eager op
        gdir, new_mean, new_var, partial = fn1(
            w, g_shard, self._states[b]["mean"], self._states[b]["var"],
            master, wd_vec, seg_ids, jnp.float32(opt.beta1),
            jnp.float32(1.0 - opt.beta1), jnp.float32(opt.beta2),
            jnp.float32(1.0 - opt.beta2),
            jnp.float32(1.0 - opt.beta1 ** t),
            jnp.float32(1.0 - opt.beta2 ** t), jnp.float32(opt.epsilon),
            rescale, clip_v)
        self._states[b]["mean"] = new_mean
        self._states[b]["var"] = new_var

        context = "bucket=[%s] lamb norms world=%d" % (spec.key_range(),
                                                       self.comm.world)

        def exchange(partial=partial, spec=spec, context=context):
            _faults.check("collective.all_reduce", context=context)
            return self.comm.all_reduce(spec, partial)

        _telem.inc("comm.collectives")
        _telem.inc("comm.all_reduce")
        full = call_with_retry(exchange, site="collective.all_reduce",
                               context=context)
        full = jnp.asarray(full)
        r1 = jnp.sqrt(full[0])
        r2 = jnp.sqrt(full[1])
        if opt.lower_bound is not None and opt.lower_bound > 0:
            r1 = jnp.maximum(r1, opt.lower_bound)
        if opt.upper_bound is not None and opt.upper_bound > 0:
            r1 = jnp.minimum(r1, opt.upper_bound)
        ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                          jnp.ones_like(r1))
        scale_vec = lr_vec * jnp.take(ratio, seg_ids)
        fn2 = _fops.lamb_flat_apply_fn(mp)
        return fn2(w, master, gdir, scale_vec)

    # -- checkpointing ---------------------------------------------------
    def state_payload(self):
        """World-size-independent state dict: the frozen layout plus the
        FULL (all-gathered, unpadded) flat state per bucket as numpy
        arrays. Shape: ``{"zero_format": 1, "layout": {...},
        "state": {bucket_index: {slot: ndarray}}}`` — pickleable by
        `SnapshotCheckpointer`, orbax-friendly as a pytree of arrays."""
        if self.layout is None:
            return {"zero_format": 1, "layout": None, "state": {},
                    "comm_schedule": _engine.schedule_payload()}
        state = {}
        for spec in self.layout:
            slots = {}
            for name, shard in self._states.get(spec.index, {}).items():
                full = self.comm.all_gather(spec, shard)
                slots[name] = _np.asarray(full[:spec.size])
            if spec.index in self._masters:
                full = self.comm.all_gather(spec, self._masters[spec.index])
                slots["master"] = _np.asarray(full[:spec.size])
            state[spec.index] = slots
        return {"zero_format": 1, "layout": self.layout.to_payload(),
                "state": state,
                "comm_schedule": _engine.schedule_payload()}

    def load_state_payload(self, payload):
        """Inverse of `state_payload`, re-partitioned for THIS comm's
        world/rank — restoring onto a different world size just slices
        different shard boundaries out of the same full state. Weight
        shards re-seed from the store on the next step (the store holds
        the restored parameters)."""
        if int(payload.get("zero_format", -1)) != 1:
            raise ValueError("not a ZeRO state payload: %r"
                             % (payload.get("zero_format"),))
        if payload.get("comm_schedule") is not None:
            # the autotuned comm schedule rides the optimizer state: a
            # restart resumes the winning schedule with 0 sweep steps
            _engine.restore_schedule(payload["comm_schedule"])
        self._w_shards.clear()
        self._masters.clear()
        self._states.clear()
        self._mult_cache.clear()   # shard boundaries may have moved
        self._seg_cache.clear()
        if payload["layout"] is None:
            self.layout = None
            return
        self.layout = _engine.BucketLayout.from_payload(
            payload["layout"], world=self.comm.world)
        lo_of = lambda spec: self.comm.rank * spec.shard  # noqa: E731
        for spec in self.layout:
            slots = {str(k): v
                     for k, v in payload["state"].get(spec.index, {}).items()}
            if not slots:
                # int keys survive pickle but not every codec; try str
                slots = {str(k): v for k, v in payload["state"].get(
                    str(spec.index), {}).items()}
            lo = lo_of(spec)
            out = {}
            for name, full in slots.items():
                full = _np.asarray(full)
                padded = _np.zeros((spec.padded,), full.dtype)
                padded[:spec.size] = full
                shard = jnp.asarray(padded[lo:lo + spec.shard])
                if name == "master":
                    self._masters[spec.index] = shard
                else:
                    out[name] = shard
            self._states[spec.index] = out
        self._update_state_gauge()

    def get_states(self, dump_optimizer=False):
        """Updater-compatible serialization (Trainer.save_states /
        kvstore.save_optimizer_states ride this unchanged)."""
        payload = self.state_payload()
        if dump_optimizer:
            payload["optimizer"] = self.optimizer
        return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)

    def set_states(self, states):
        payload = pickle.loads(states)
        if "optimizer" in payload:
            self.optimizer = payload.pop("optimizer")
        self.load_state_payload(payload)


def get_zero_updater(optimizer, comm=None):
    """`optimizer.get_updater` analog for the ZeRO path."""
    return ZeroUpdater(optimizer, comm=comm)
