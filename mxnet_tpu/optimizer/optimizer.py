"""Optimizer registry + implementations.

TPU-native analog of reference python/mxnet/optimizer/optimizer.py. Same
registry (`@Optimizer.register`, `create_optimizer`), same state protocol
(`create_state` / `update` / multi-precision fp32 master weights), same
`lr_mult`/`wd_mult` resolution order, and the same serializable `Updater`
(the object the reference pickles and ships to parameter servers via
`kvstore.set_optimizer`).

Update rules execute through the optimizer ops registered in
mxnet_tpu/ops/optimizer_ops.py (reference: src/operator/optimizer_op.cc), so
eager calls are one fused XLA computation each, and a jitted trainer step
fuses them into the whole-step graph.
"""
from __future__ import annotations

import logging
import math
import pickle
import warnings

import numpy as _np
import jax.numpy as jnp

from .. import ndarray as nd
from ..ndarray import NDArray
from ..ops import registry as _reg

__all__ = ["Optimizer", "create", "register", "get_updater", "Updater",
           "SGD", "Signum", "SignSGD", "FTML", "LARS", "DCASGD", "NAG",
           "SGLD", "Adam", "AdaGrad", "AdaDelta", "Adamax", "Nadam",
           "RMSProp", "Ftrl", "LAMB", "AdamW", "LBSGD", "Test"]


def _run_op(name, *arrays, **kwargs):
    """Execute an optimizer op on NDArray payloads, writing results back
    in-place — the reference's out=weight convention. Every optimizer op
    takes (weight, grad, *states) and returns (weight, *states): the grad
    input is read-only and produces no output.

    row_sparse grads with lazy_update=True take the lazy path (reference:
    optimizer_op.cc rowsparse kernels): only rows present in grad.indices
    are touched — momentum/history of absent rows is NOT decayed.

    Dispatch resolves through `registry.best_fn` (like the ndarray invoke
    layer) so the Pallas tpu_impl overrides in ops/fused_optimizer.py
    serve the eager per-parameter update path on accelerator contexts."""
    from ..ndarray.sparse import RowSparseNDArray
    op = _reg.get(name)
    fn = op.best_fn(arrays[0].context.device_type in ("gpu", "tpu"))
    grad = arrays[1] if len(arrays) > 1 else None
    if isinstance(grad, RowSparseNDArray) and kwargs.get("lazy_update") \
            and grad._indices.shape[0] < grad.shape[0]:
        idx = grad._indices
        w_full = arrays[0]._read()
        state_fulls = [a._read() for a in arrays[2:]]
        row_args = [w_full[idx], grad._values] + [s[idx] for s in state_fulls]
        out = fn(*row_args, **kwargs)
        if not isinstance(out, tuple):
            out = (out,)
        targets = [arrays[0]] + list(arrays[2:])
        fulls = [w_full] + state_fulls
        assert len(targets) == len(out)
        for target, full, new in zip(targets, fulls, out):
            target._write(full.at[idx].set(new.astype(full.dtype)))
        return
    raws = [a._read() for a in arrays]
    out = fn(*raws, **kwargs)
    if not isinstance(out, tuple):
        out = (out,)
    targets = [arrays[0]] + list(arrays[2:])
    assert len(targets) == len(out), \
        "optimizer op %s returned %d outputs for %d targets" % (
            name, len(out), len(targets))
    for target, new in zip(targets, out):
        target._write(new.astype(target._read().dtype))


# ---------------------------------------------------------------------------
# fused multi-parameter updates (reference: src/operator/optimizer_op.cc
# multi_sgd_update / multi_sgd_mom_update / multi_mp_sgd_*, surfaced by
# Optimizer.aggregate_num). One jitted call updates every parameter of a
# step — the dominant eager-trainer cost is per-op dispatch, and XLA fuses
# the whole bundle. jit caches on the list-of-shapes structure.
# ---------------------------------------------------------------------------
_FUSED_CACHE = {}


def _fused_fn(kind, momentum_on, clip_on):
    import jax as _jax
    key = (kind, momentum_on, clip_on)
    fn = _FUSED_CACHE.get(key)
    if fn is not None:
        return fn

    def prep(g, w, rescale, clip, wd):
        g = g.astype(jnp.float32) * rescale
        if clip_on:
            g = jnp.clip(g, -clip, clip)
        return g + wd * w.astype(jnp.float32)

    if kind == "sgd":
        def impl(ws, gs, moms, lrs, wds, momentum, rescale, clip):
            new_w, new_m = [], []
            for i, (w, g) in enumerate(zip(ws, gs)):
                g32 = prep(g, w, rescale, clip, wds[i])
                if momentum_on:
                    m = moms[i].astype(jnp.float32) * momentum - lrs[i] * g32
                    new_m.append(m.astype(moms[i].dtype))
                    new_w.append((w.astype(jnp.float32) + m).astype(w.dtype))
                else:
                    new_w.append((w.astype(jnp.float32) - lrs[i] * g32)
                                 .astype(w.dtype))
            return new_w, new_m
    elif kind == "adam":
        def impl(ws, gs, means, variances, lrs, wds, beta1, beta2, eps,
                 rescale, clip):
            new_w, new_m, new_v = [], [], []
            for i, (w, g) in enumerate(zip(ws, gs)):
                g32 = prep(g, w, rescale, clip, wds[i])
                m = beta1 * means[i] + (1.0 - beta1) * g32
                v = beta2 * variances[i] + (1.0 - beta2) * g32 * g32
                new_m.append(m)
                new_v.append(v)
                new_w.append((w.astype(jnp.float32) -
                              lrs[i] * m / (jnp.sqrt(v) + eps))
                             .astype(w.dtype))
            return new_w, new_m, new_v
    else:
        raise KeyError(kind)

    fn = _FUSED_CACHE[key] = _jax.jit(impl)
    return fn


def _fused_flat_fn(kind, momentum_on, clip_on, mp_on):
    """ONE fused pass over a flat parameter SHARD — the ZeRO-1 update
    kernel (reference blueprint: "Tensor Processing Primitives", PAPERS.md:
    one fused sweep over params+grads+momentum instead of three).

    Dispatcher (ISSUE 10): when the Pallas optimizer layer is requested
    (`ops.fused_optimizer.use_pallas_flat` — interpreter runs, or TPU +
    MXNET_TPU_USE_PALLAS), the returned callable is the Pallas
    flat-segment kernel, with counted automatic fallback to the XLA
    composite for ineligible operands; otherwise it is `_fused_flat_xla`,
    the always-available XLA escape hatch. Both share one signature per
    kind and the same elementwise arithmetic (bit-identical on the
    interpreter — tests assert it)."""
    from ..ops import fused_optimizer as _fops
    if kind in ("sgd", "adam") and _fops.use_pallas_flat():
        return _fops.flat_update_fn(kind, momentum_on, clip_on, mp_on)
    return _fused_flat_xla(kind, momentum_on, clip_on, mp_on)


def _fused_flat_xla(kind, momentum_on, clip_on, mp_on):
    """The XLA composite flat-shard update (pre-ISSUE-10 `_fused_flat_fn`
    body): one jitted pass taking a single contiguous flat buffer per
    operand (one dtype-bucket's owned shard, `mx.engine.BucketSpec`):
    weight, grad, and state are 1-D vectors, and lr/wd arrive as
    per-ELEMENT vectors (host-built from the bucket's shard_segments, so
    per-parameter lr_mult/wd_mult and Adam bias correction survive the
    flattening; padding tail elements carry lr=wd=0). `mp_on` threads an
    fp32 master shard for fp16 weights (the multi-precision contract of
    `mp_sgd_*`): math runs on the master, the returned weight is cast to
    the wire dtype for the all-gather.

    Arithmetic matches `_fused_fn`/the optimizer ops elementwise, so the
    ZeRO path stays bit-identical to the replicated update on fp32."""
    import jax as _jax
    key = ("flat", kind, momentum_on, clip_on, mp_on)
    fn = _FUSED_CACHE.get(key)
    if fn is not None:
        return fn

    def prep(g, w32, rescale, clip, wd_vec):
        g = g.astype(jnp.float32) * rescale
        if clip_on:
            g = jnp.clip(g, -clip, clip)
        return g + wd_vec * w32

    if kind == "sgd":
        def impl(w, g, mom, master, lr_vec, wd_vec, momentum, rescale,
                 clip):
            w32 = master if mp_on else w.astype(jnp.float32)
            g32 = prep(g, w32, rescale, clip, wd_vec)
            if momentum_on:
                m = mom.astype(jnp.float32) * momentum - lr_vec * g32
                new_mom = m.astype(mom.dtype)
                w32n = w32 + m
            else:
                new_mom = mom
                w32n = w32 - lr_vec * g32
            return (w32n.astype(w.dtype), new_mom,
                    w32n if mp_on else master)
    elif kind == "adam":
        # omb1/omb2 = 1-beta1 / 1-beta2 computed by the CALLER in python
        # double (as the eager op path does) — deriving them in-trace from
        # the f32 betas rounds differently and breaks bit parity
        def impl(w, g, mean, var, master, lr_vec, wd_vec, beta1, omb1,
                 beta2, omb2, eps, rescale, clip):
            w32 = master if mp_on else w.astype(jnp.float32)
            g32 = prep(g, w32, rescale, clip, wd_vec)
            m = beta1 * mean + omb1 * g32
            v = beta2 * var + omb2 * g32 * g32
            w32n = w32 - lr_vec * m / (jnp.sqrt(v) + eps)
            return (w32n.astype(w.dtype), m.astype(mean.dtype),
                    v.astype(var.dtype), w32n if mp_on else master)
    else:
        raise KeyError(kind)

    fn = _FUSED_CACHE[key] = _jax.jit(impl)
    return fn


class Optimizer:
    """Base optimizer. reference: python/mxnet/optimizer/optimizer.py."""

    opt_registry = {}

    def __init__(self, rescale_grad=1., param_idx2name=None, wd=0.,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._all_index_update_counts = {0: {}}
        self._index_update_count = self._all_index_update_counts[0]
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not \
            None else ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    @staticmethod
    def register(klass):
        """reference: Optimizer.register — lowercased class name."""
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            warnings.warn("WARNING: New optimizer %s.%s is overriding "
                          "existing optimizer %s" %
                          (klass.__module__, klass.__name__, name))
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        """reference: Optimizer.create_optimizer."""
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def create_state(self, index, weight):
        """Create auxiliary state for `weight`."""
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master copy for fp16 weights. reference:
        create_state_multi_precision."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy = weight.astype(_np.float32)
            return (weight_master_copy, self.create_state(index,
                                                          weight_master_copy))
        if weight.dtype == _np.float16 and not self.multi_precision:
            warnings.warn("Accumulating with float16 in optimizer can lead "
                          "to poor accuracy or slow convergence. Consider "
                          "using multi_precision=True option of the optimizer")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        """reference: update_multi_precision."""
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy, original_state = state
            grad32 = grad.astype(_np.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight._write(weight_master_copy._read().astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """reference: Optimizer.set_lr_mult (reads __lr_mult__ sym attrs)."""
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """reference: Optimizer.set_wd_mult — biases/gammas/betas default to
        wd_mult 0."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _set_current_context(self, device_id):
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        """reference: Optimizer._get_lrs — scheduler + per-param lr_mult."""
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        del ret["_all_index_update_counts"]
        return ret

    def __setstate__(self, state):
        self.__dict__ = state
        self._all_index_update_counts = {0: self._index_update_count}


register = Optimizer.register  # convenience, reference exports it


def create(name, **kwargs):
    """reference: mx.optimizer.create."""
    if isinstance(name, Optimizer):
        return name
    return Optimizer.create_optimizer(name, **kwargs)


def _clip(v):
    return -1.0 if v is None else v


@register
class SGD(Optimizer):
    """SGD with momentum + multi-precision. reference: optimizer.py (SGD).

    state = momentum buffer (or None); update runs the sgd_update /
    sgd_mom_update ops (reference: src/operator/optimizer_op.cc)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state,
                          multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype == _np.float16
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)

    def fused_update(self, indices, weights, grads, states):
        """Aggregated multi-param step in one jitted call (reference:
        multi_sgd_update / multi_sgd_mom_update)."""
        for i in indices:
            self._update_count(i)
        lrs = [jnp.float32(self._get_lr(i)) for i in indices]
        wds = [jnp.float32(self._get_wd(i)) for i in indices]
        clip = self.clip_gradient
        fn = _fused_fn("sgd", self.momentum != 0.0, clip is not None)
        ws = [w._read() for w in weights]
        gs = [g._read() for g in grads]
        moms = [s._read() for s in states] if self.momentum else []
        new_w, new_m = fn(ws, gs, moms, lrs, wds,
                          jnp.float32(self.momentum),
                          jnp.float32(self.rescale_grad),
                          jnp.float32(clip if clip is not None else 0.0))
        for w, nw in zip(weights, new_w):
            w._write(nw)
        for s, nm in zip(states, new_m):
            s._write(nm)

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient),
                      lazy_update=self.lazy_update)
        if not multi_precision:
            if state is not None:
                _run_op("sgd_mom_update", weight, grad, state,
                        momentum=self.momentum, **kwargs)
            else:
                _run_op("sgd_update", weight, grad, **kwargs)
        else:
            w32, mom = state
            if mom is not None:
                _run_op("mp_sgd_mom_update", weight, grad, mom, w32,
                        momentum=self.momentum, **kwargs)
            else:
                _run_op("mp_sgd_update", weight, grad, w32, **kwargs)


@register
class Signum(Optimizer):
    """reference: optimizer.py (Signum) — sign of momentum step."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            _run_op("signum_update", weight, grad, state,
                    momentum=self.momentum, wd_lh=self.wd_lh, **kwargs)
        else:
            _run_op("signsgd_update", weight, grad, **kwargs)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    """reference: optimizer.py (FTML)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        _run_op("ftml_update", weight, grad, d, v, z, lr=lr, wd=wd,
                beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                rescale_grad=self.rescale_grad,
                clip_grad=_clip(self.clip_gradient), t=t)


@register
class LARS(Optimizer):
    """LARS: layer-wise rate scaling on top of SGD-momentum.
    reference: optimizer.py (LARS)."""

    def __init__(self, momentum=0.0, lazy_update=True, eta=0.001, eps=1e-8,
                 **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        self.eta = eta
        self.eps = eps

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def _l2norm(self, v):
        return float(v.norm().asscalar())

    def _get_lars(self, i, weight, g, lr, wd):
        name = self.idx2name.get(i, str(i))
        if name.endswith("gamma") or name.endswith("beta") or \
                name.endswith("bias"):
            return lr
        w_norm = self._l2norm(weight)
        g_norm = self._l2norm(g)
        if w_norm > 0.0 and g_norm > 0.0:
            lars = self.eta * w_norm / (g_norm + wd * w_norm + self.eps)
        else:
            lars = 1.0
        return lars * lr

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        lr = self._get_lars(index, weight, grad, lr, wd)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            _run_op("sgd_mom_update", weight, grad, state,
                    momentum=self.momentum, **kwargs)
        else:
            _run_op("sgd_update", weight, grad, **kwargs)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD. reference: optimizer.py (DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            import jax.numpy as jnp
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        w = weight._read()
        pw = previous_weight._read()
        step = -lr * (g + wd * w + self.lamda * g * g * (w - pw))
        if mom is not None:
            m = self.momentum * mom._read() + step
            mom._write(m)
            step = m
        previous_weight._write(w)
        weight._write(w + step)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD. reference: optimizer.py (NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype == _np.float16
        self._update_impl(index, weight, grad, state, multi_precision=use_mp)

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient))
        if not multi_precision:
            if state is not None:
                _run_op("nag_mom_update", weight, grad, state,
                        momentum=self.momentum, **kwargs)
            else:
                _run_op("sgd_update", weight, grad, **kwargs)
        else:
            w32, mom = state
            if mom is not None:
                _run_op("mp_nag_mom_update", weight, grad, mom, w32,
                        momentum=self.momentum, **kwargs)
            else:
                _run_op("mp_sgd_update", weight, grad, w32, **kwargs)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics. reference: optimizer.py (SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        import jax.numpy as jnp
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        from .. import random as _random
        import jax
        noise = jax.random.normal(_random.take_key(weight.context),
                                  weight.shape, dtype=weight._read().dtype) \
            * math.sqrt(lr)
        w = weight._read()
        weight._write(w - lr / 2 * (g + wd * w) + noise)


@register
class Adam(Optimizer):
    """reference: optimizer.py (Adam) — bias correction folded into lr."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        _run_op("adam_update", weight, grad, mean, var, lr=lr, wd=wd,
                beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient),
                lazy_update=self.lazy_update)

    def fused_update(self, indices, weights, grads, states):
        """Aggregated adam step, bias correction folded into per-param lr
        (same trick as the reference's multi-tensor adam)."""
        lrs, wds = [], []
        for i in indices:
            self._update_count(i)
            t = self._index_update_count[i]
            lr = self._get_lr(i) * math.sqrt(1. - self.beta2 ** t) / \
                (1. - self.beta1 ** t)
            lrs.append(jnp.float32(lr))
            wds.append(jnp.float32(self._get_wd(i)))
        clip = self.clip_gradient
        fn = _fused_fn("adam", True, clip is not None)
        ws = [w._read() for w in weights]
        gs = [g._read() for g in grads]
        means = [s[0]._read() for s in states]
        variances = [s[1]._read() for s in states]
        new_w, new_m, new_v = fn(
            ws, gs, means, variances, lrs, wds, jnp.float32(self.beta1),
            jnp.float32(self.beta2), jnp.float32(self.epsilon),
            jnp.float32(self.rescale_grad),
            jnp.float32(clip if clip is not None else 0.0))
        for w, nw in zip(weights, new_w):
            w._write(nw)
        # keep state dtype as created (eager _run_op casts the same way)
        for s, nm, nv, m0, v0 in zip(states, new_m, new_v, means, variances):
            s[0]._write(nm.astype(m0.dtype))
            s[1]._write(nv.astype(v0.dtype))


@register
class AdaGrad(Optimizer):
    """reference: optimizer.py (AdaGrad)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        _run_op("adagrad_update", weight, grad, state, lr=lr, wd=wd,
                epsilon=self.float_stable_eps,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient))


@register
class AdaDelta(Optimizer):
    """reference: optimizer.py (AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        _run_op("adadelta_update", weight, grad, acc_g, acc_delta,
                rho=self.rho, epsilon=self.epsilon, wd=wd,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient))


@register
class Adamax(Optimizer):
    """reference: optimizer.py (Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        mean, u = state
        _run_op("adamax_update", weight, grad, mean, u, lr=lr, wd=wd,
                beta1=self.beta1, beta2=self.beta2,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient))


@register
class Nadam(Optimizer):
    """reference: optimizer.py (Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        _run_op("nadam_update", weight, grad, mean, var, lr=lr, wd=wd,
                beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                schedule_decay=self.schedule_decay,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient), t=t,
                m_schedule=self.m_schedule)
        self.m_schedule = self.m_schedule * momentum_t


@register
class RMSProp(Optimizer):
    """reference: optimizer.py (RMSProp) — centered=True uses Graves 2013."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                      rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient),
                      clip_weights=_clip(self.clip_weights))
        if not self.centered:
            _run_op("rmsprop_update", weight, grad, state, **kwargs)
        else:
            n, g, delta = state
            _run_op("rmspropalex_update", weight, grad, n, g, delta,
                    gamma2=self.gamma2, **kwargs)


@register
class Ftrl(Optimizer):
    """reference: optimizer.py (Ftrl)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        z, n = state
        _run_op("ftrl_update", weight, grad, z, n, lr=lr, wd=wd,
                lamda1=self.lamda1, beta=self.beta,
                rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient))


@register
class LAMB(Optimizer):
    """reference: optimizer.py (LAMB) — layer-wise adaptive large-batch."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        op1 = _reg.get("lamb_update_phase1")
        g_raw, mean_new, var_new = op1.fn(
            weight._read(), grad._read(), mean._read(), var._read(),
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, t=t,
            bias_correction=self.bias_correction, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=_clip(self.clip_gradient))
        mean._write(mean_new)
        var._write(var_new)
        import jax.numpy as jnp
        r1 = jnp.linalg.norm(weight._read())
        r2 = jnp.linalg.norm(g_raw)
        op2 = _reg.get("lamb_update_phase2")
        weight._write(op2.fn(weight._read(), g_raw, r1, r2, lr=lr,
                             lower_bound=_clip(self.lower_bound),
                             upper_bound=_clip(self.upper_bound)))


@register
class AdamW(Optimizer):
    """Decoupled weight decay Adam. reference:
    python/mxnet/contrib/optimizer (adamw) / src/operator/contrib/adamw.cc."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.eta = eta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        _run_op("adamw_update", weight, grad, mean, var, lr=lr, wd=wd,
                beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                eta=self.eta, rescale_grad=self.rescale_grad,
                clip_gradient=_clip(self.clip_gradient))


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS-style scaling + warmup.
    reference: optimizer.py (LBSGD). Implemented on the sgd-mom kernels with
    the reference's lars scaling formula."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        strategy = self.warmup_strategy
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            mult = maxmult
        elif nwup <= 1:
            mult = 1.0
        else:
            if strategy == "linear":
                mult = 1.0 + (maxmult - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
            else:
                mult = 1.0
        return mult

    def _get_lars(self, weight, g, wd):
        """LARS layer rate for warmup_strategy='lars'
        (reference: LBSGD._get_lars)."""
        weight2 = float((weight * weight).sum().asscalar())
        grad2 = float((g * g).sum().asscalar())
        lars = math.sqrt(weight2 / (grad2 + wd * weight2 + 1e-18))
        return min(max(lars, 0.01), 100.0)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if self.warmup_strategy == "lars":
            self.lbmult = self._get_lars(weight, grad, wd)
        else:
            num_update = self.num_update + self.init_updates
            self.lbmult = self._get_lbmult(num_update)
        lr = lr * self.lbmult
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            _run_op("sgd_mom_update", weight, grad, state,
                    momentum=self.momentum, **kwargs)
        else:
            _run_op("sgd_update", weight, grad, **kwargs)


@register
class Test(Optimizer):
    """Trivial optimizer used by reference unit tests."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._write(weight._read() - grad._read() * self.rescale_grad)


class Updater:
    """The function-object applied per (key, grad, weight) — serializable so
    it can be shipped to parameter-server processes.
    reference: optimizer.py (Updater, get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        indices = index if isinstance(index, (list, tuple)) else [index]
        grads = grad if isinstance(grad, (list, tuple)) else [grad]
        weights = weight if isinstance(weight, (list, tuple)) else [weight]
        for idx, w in zip(indices, weights):
            if idx not in self.states:
                self.states[idx] = \
                    self.optimizer.create_state_multi_precision(idx, w)
                self.states_synced[idx] = True
            elif not self.states_synced[idx]:
                self.states[idx] = self.sync_state_context(self.states[idx],
                                                           w.context)
                self.states_synced[idx] = True
        if len(indices) > 1 and self.aggregate_updates and \
                self._can_fuse(weights, grads):
            self.optimizer.fused_update(
                indices, weights, grads,
                [self.states[i] for i in indices])
            return
        for idx, g, w in zip(indices, grads, weights):
            self.optimizer.update_multi_precision(idx, w, g, self.states[idx])

    def _can_fuse(self, weights, grads):
        """Aggregated update only for exactly SGD/Adam (subclasses override
        update semantics), dense grads, non-fp16 weights (fp16 goes the
        multi-precision path). Gated by optimizer.aggregate_num (reference:
        MXNET_OPTIMIZER_AGGREGATION_SIZE)."""
        from ..ndarray.sparse import BaseSparseNDArray
        if type(self.optimizer) not in (SGD, Adam):
            return False
        if any(isinstance(g, BaseSparseNDArray) for g in grads):
            return False
        return all(w.dtype != _np.float16 for w in weights)

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            synced = [self.sync_state_context(i, context) for i in state]
            return tuple(synced) if isinstance(state, tuple) else synced
        return state

    def set_states(self, states):
        """Deserialize states (reference: Updater.set_states)."""
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        """Serialize states (+ optionally the optimizer itself)."""
        return pickle.dumps((self.states, self.optimizer) if dump_optimizer
                            else self.states)


def get_updater(optimizer):
    """reference: optimizer.py (get_updater)."""
    return Updater(optimizer)


# NDArray needs pickling support for Updater serialization
def _ndarray_reduce(arr):
    return (_ndarray_rebuild, (arr.asnumpy(), str(arr.context.device_type),
                               arr.context.device_id))


def _ndarray_rebuild(data, dev_type, dev_id):
    from ..context import Context
    return nd.array(data, ctx=Context(dev_type, dev_id), dtype=data.dtype)


import copyreg  # noqa: E402
copyreg.pickle(NDArray, _ndarray_reduce)
