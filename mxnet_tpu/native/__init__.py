"""ctypes bindings for the native host kernels (native/mxnet_tpu_native.cc).

The reference ships its IO stack in C++ (dmlc RecordIO, the image decode/
augment thread pool); this module is the TPU build's equivalent. The shared
library is compiled lazily with g++ on first use and cached next to the
source; every caller falls back to pure python when the toolchain or build
is unavailable, so the package never hard-depends on it.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as _np

__all__ = ["available", "lib", "index_recordio_buffer", "batch_to_chw_norm",
           "img_to_chw_norm"]

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "mxnet_tpu_native.cc")
_OUT = os.path.join(os.path.dirname(_SRC), "_build",
                    "libmxnet_tpu_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    os.makedirs(os.path.dirname(_OUT), exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
           _SRC, "-o", _OUT]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        # retry without OpenMP (toolchains lacking libgomp)
        try:
            subprocess.run([a for a in cmd if a != "-fopenmp"], check=True,
                           capture_output=True, timeout=120)
            return True
        except (OSError, subprocess.SubprocessError):
            return False


def lib():
    """The loaded CDLL, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_OUT) or (
                os.path.exists(_SRC) and
                os.path.getmtime(_SRC) > os.path.getmtime(_OUT)):
            # holding _lock across the compile is the point: concurrent
            # first callers must WAIT for the one build, not race g++ or
            # observe a half-written .so — and nothing else ever contends
            # for this lock after the first call resolves
            if not os.path.exists(_SRC) or not _build():  # tpu-lint: disable=TPU010
                return None
        try:
            cdll = ctypes.CDLL(_OUT)
        except OSError:
            return None
        cdll.mxtpu_recordio_index.restype = ctypes.c_int64
        cdll.mxtpu_recordio_index.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64]
        cdll.mxtpu_img_to_chw_norm.restype = None
        cdll.mxtpu_batch_to_chw_norm.restype = None
        _lib = cdll
    return _lib


def available():
    return lib() is not None


def index_recordio_buffer(buf):
    """Index a .rec byte buffer → (starts, sizes) int64 arrays of logical
    records (reference: dmlc::RecordIOReader framing scan). Returns None
    when the native lib is unavailable (callers fall back to python)."""
    cdll = lib()
    if cdll is None:
        return None
    n = len(buf)
    cap = max(16, n // 8)       # worst case: empty payloads, 8B per record
    starts = _np.empty(cap, _np.int64)
    sizes = _np.empty(cap, _np.int64)
    count = cdll.mxtpu_recordio_index(
        buf, n, starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), cap)
    if count == -1:
        raise IOError("Invalid RecordIO magic number")
    if count == -2:  # capacity exceeded (adversarial framing); python path
        return None
    return starts[:count].copy(), sizes[:count].copy()


def img_to_chw_norm(img, mean=None, std=None):
    """uint8 HWC image → normalized float32 CHW, one fused pass."""
    cdll = lib()
    img = _np.ascontiguousarray(img, dtype=_np.uint8)
    h, w, c = img.shape
    if cdll is None:
        out = img.astype(_np.float32) / 255.0
        if mean is not None:
            out = out - _np.asarray(mean, _np.float32)
        if std is not None:
            out = out / _np.asarray(std, _np.float32)
        return out.transpose(2, 0, 1).copy()
    dst = _np.empty((c, h, w), _np.float32)
    mean_p = (_np.ascontiguousarray(mean, _np.float32)
              .ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              if mean is not None else None)
    std_p = (_np.ascontiguousarray(std, _np.float32)
             .ctypes.data_as(ctypes.POINTER(ctypes.c_float))
             if std is not None else None)
    cdll.mxtpu_img_to_chw_norm(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, c,
        mean_p, std_p, dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return dst


def batch_to_chw_norm(batch, mean=None, std=None):
    """uint8 (B,H,W,C) → float32 (B,C,H,W) normalized, OpenMP across the
    batch (reference: ImageRecordIOParser2's decode thread pool)."""
    cdll = lib()
    batch = _np.ascontiguousarray(batch, dtype=_np.uint8)
    b, h, w, c = batch.shape
    if cdll is None:
        return _np.stack([img_to_chw_norm(im, mean, std) for im in batch])
    dst = _np.empty((b, c, h, w), _np.float32)
    mean_p = (_np.ascontiguousarray(mean, _np.float32)
              .ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              if mean is not None else None)
    std_p = (_np.ascontiguousarray(std, _np.float32)
             .ctypes.data_as(ctypes.POINTER(ctypes.c_float))
             if std is not None else None)
    cdll.mxtpu_batch_to_chw_norm(
        batch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), b, h, w, c,
        mean_p, std_p, dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return dst
