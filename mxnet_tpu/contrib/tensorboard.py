"""TensorBoard logging callback (reference: python/mxnet/contrib/
tensorboard.py — a thin wrapper over the external `tensorboard`/`mxboard`
SummaryWriter; the reference also hard-depends on that pip package and
raises at use if it is absent).
"""
from __future__ import annotations

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Log eval metrics to TensorBoard event files each time it is invoked
    (pass as `eval_metric_callback` / batch-end callback to `fit`).

    reference: contrib/tensorboard.py (LogMetricsCallback). Requires the
    external `tensorboardX`/`tensorboard` package, exactly like the
    reference; constructing without one raises ImportError.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        writer_cls = None
        for mod, attr in (("tensorboardX", "SummaryWriter"),
                          ("torch.utils.tensorboard", "SummaryWriter")):
            try:
                writer_cls = getattr(__import__(mod, fromlist=[attr]), attr)
                break
            except ImportError:
                continue
        if writer_cls is None:
            raise ImportError(
                "LogMetricsCallback requires a TensorBoard SummaryWriter "
                "(pip install tensorboardX), matching the reference's "
                "external dependency")
        self.summary_writer = writer_cls(logging_dir)
        self.step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
