"""INT8 model quantization: calibration + Gluon network conversion.

reference: python/mxnet/contrib/quantization.py (quantize_model,
quantize_net, _LayerOutputMinMaxCollector, _calibrate_quantized_sym via
src/operator/quantization/calibrate.cc).

Pipeline (same shape as the reference):
  1. collect per-layer INPUT statistics by running calibration batches
     through the fp32 net (naive min/max, or KL-entropy thresholds over a
     histogram — the calibrate.cc algorithm);
  2. replace Dense/Conv2D blocks with quantized twins holding int8 weights
     (per-output-channel symmetric scales) and the calibrated activation
     threshold;
  3. the quantized forward quantizes the input once, runs the int8
     dot/conv with int32 accumulation on the MXU, and dequantizes into the
     fp32 stream — XLA fuses the (de)quantize elementwise work into the
     surrounding ops.
"""
from __future__ import annotations

import logging

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .. import ndarray as nd
from ..context import cpu
from ..gluon import nn as _nn
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "quantize_model", "calib_thresholds",
           "QuantizedDense", "QuantizedConv2D"]

INT8_MAX = 127.0


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
class _Collector:
    """Per-layer input statistics: running abs-max and a histogram for the
    entropy mode (reference: _LayerHistogramCollector)."""

    def __init__(self, bins=2048):
        self.bins = bins
        self.absmax = {}
        self.hist = {}

    def update(self, name, arr):
        a = _np.abs(_np.asarray(arr, dtype=_np.float32)).ravel()
        m = float(a.max()) if a.size else 0.0
        old = self.absmax.get(name, 0.0)
        if name in self.hist:
            h, edges = self.hist[name]
            if m > old:  # re-bin the old histogram onto the wider range
                new_edges = _np.linspace(0, m, self.bins + 1)
                centers = (edges[:-1] + edges[1:]) / 2
                nh, _ = _np.histogram(centers, bins=new_edges, weights=h)
                h, edges = nh, new_edges
            h += _np.histogram(a, bins=edges)[0]
            self.hist[name] = (h, edges)
        else:
            edges = _np.linspace(0, max(m, 1e-12), self.bins + 1)
            self.hist[name] = (_np.histogram(a, bins=edges)[0]
                               .astype(_np.float64), edges)
        self.absmax[name] = max(old, m)


def _smooth_distribution(d, eps=1e-4):
    """Move eps mass onto zero entries so KL terms stay finite.
    reference: python/mxnet/contrib/quantization.py (_smooth_distribution)."""
    d = d.astype(_np.float64).copy()
    zeros = d == 0
    n_zero, n_nonzero = zeros.sum(), (~zeros).sum()
    if n_zero and n_nonzero:
        d[~zeros] -= eps * n_zero / n_nonzero
        d[zeros] = eps
    return d


def _entropy_threshold(hist, edges, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold over an abs-value histogram.
    reference: src/operator/quantization/calibrate.cc (GetOptimalThreshold)."""
    total = hist.sum()
    if total == 0:
        return float(edges[-1])
    best_kl, best_t = _np.inf, float(edges[-1])
    # candidate thresholds from num_quantized_bins//2 bins upward. P is the
    # clipped distribution (outlier mass collapsed onto the edge bin); Q is
    # the UNclipped slice quantized to num_quantized_bins — so clipping mass
    # shows up as P/Q mismatch at the edge and is penalized (the TensorRT /
    # calibrate.cc construction).
    start = num_quantized_bins // 2
    for i in range(start, len(hist) + 1, max(1, len(hist) // 128)):
        t = edges[i]
        sliced = hist[:i].astype(_np.float64)
        if sliced.sum() == 0:
            continue
        p = sliced.copy()
        p[-1] += hist[i:].sum()                # clip mass onto the edge
        # quantize the unclipped slice into num_quantized_bins, expand back
        factor = len(sliced) / num_quantized_bins
        q = _np.zeros_like(sliced)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = max(int((j + 1) * factor), lo + 1)
            chunk = sliced[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = _np.where(chunk > 0, chunk.sum() / nz, 0)
        p, q = _smooth_distribution(p), _smooth_distribution(q)
        pn, qn = p / p.sum(), q / q.sum()
        kl = float((pn * _np.log(pn / qn)).sum())
        if kl < best_kl:
            best_kl, best_t = kl, float(t)
    return best_t


def calib_thresholds(collector, mode="entropy"):
    """{layer_name: activation clip threshold} from collected stats."""
    if mode == "naive":
        return dict(collector.absmax)
    return {name: _entropy_threshold(h, e)
            for name, (h, e) in collector.hist.items()}


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------
def _quantize_weight(w, axis=0):
    """Symmetric per-output-channel int8 weights. Returns (int8, scales)."""
    red = tuple(i for i in range(w.ndim) if i != axis)
    t = _np.maximum(_np.abs(w).max(axis=red, keepdims=False), 1e-30)
    scale = INT8_MAX / t
    shape = [1] * w.ndim
    shape[axis] = -1
    q = _np.clip(_np.round(w * scale.reshape(shape)), -INT8_MAX,
                 INT8_MAX).astype(_np.int8)
    return q, t.astype(_np.float32)  # thresholds (per out-channel)


class _QuantizedBase(HybridBlock):
    """Shared machinery: int8 weight buffers + input quantization.

    act_max None => dynamic per-batch range (calib_mode='none');
    otherwise the calibrated threshold is baked into the program.
    """

    def __init__(self, weight_np, bias_np, act_max, channel_axis=0, **kw):
        super().__init__(**kw)
        q, w_t = _quantize_weight(weight_np, axis=channel_axis)
        self._wq = jnp.asarray(q)
        self._w_t = jnp.asarray(w_t)              # per-channel thresholds
        self._bias = (jnp.asarray(bias_np, jnp.float32)
                      if bias_np is not None else None)
        self._act_max = act_max                   # python float | None

    def _quant_input(self, x32):
        if self._act_max is not None:
            t = jnp.float32(self._act_max)
        else:
            t = jnp.maximum(jnp.max(jnp.abs(x32)), 1e-30)
        scale = INT8_MAX / t
        xq = jnp.clip(jnp.round(x32 * scale), -INT8_MAX,
                      INT8_MAX).astype(jnp.int8)
        return xq, t


class QuantizedDense(_QuantizedBase):
    """int8 twin of nn.Dense. reference: quantized_fully_connected.cc via
    quantize_net's graph rewrite."""

    def __init__(self, dense, act_max, **kw):
        w = dense.weight.data().asnumpy()
        b = dense.bias.data().asnumpy() if dense.bias is not None else None
        super().__init__(w, b, act_max, channel_axis=0, **kw)
        self._flatten = dense._flatten
        self._act = dense.act

    def hybrid_forward(self, F, x):
        raw = x._read() if hasattr(x, "_read") else x

        def f(xr):
            x32 = xr.astype(jnp.float32)
            if self._flatten and x32.ndim > 2:
                x32 = x32.reshape(x32.shape[0], -1)
            xq, t_x = self._quant_input(x32)
            acc = lax.dot_general(xq, self._wq,
                                  (((x32.ndim - 1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            deq = acc.astype(jnp.float32) * (
                (t_x * self._w_t) / (INT8_MAX * INT8_MAX))
            if self._bias is not None:
                deq = deq + self._bias
            return deq

        out = nd.from_jax(f(raw), ctx=x.context) \
            if hasattr(x, "_read") else f(raw)
        return self._act(out) if self._act is not None else out


class QuantizedConv2D(_QuantizedBase):
    """int8 twin of nn.Conv2D (NCHW). reference: quantized_conv.cc."""

    def __init__(self, conv, act_max, **kw):
        w = conv.weight.data().asnumpy()
        b = conv.bias.data().asnumpy() if conv.bias is not None else None
        super().__init__(w, b, act_max, channel_axis=0, **kw)
        self._stride = conv._kwargs.get("stride", (1, 1))
        self._pad = conv._kwargs.get("pad", (0, 0))
        self._dilate = conv._kwargs.get("dilate", (1, 1))
        self._groups = conv._kwargs.get("num_group", 1)
        self._act = getattr(conv, "act", None)

    def hybrid_forward(self, F, x):
        raw = x._read() if hasattr(x, "_read") else x

        def f(xr):
            x32 = xr.astype(jnp.float32)
            xq, t_x = self._quant_input(x32)
            dn = lax.conv_dimension_numbers(
                xq.shape, self._wq.shape, ("NCHW", "OIHW", "NCHW"))
            acc = lax.conv_general_dilated(
                xq, self._wq, window_strides=tuple(self._stride),
                padding=[(p, p) for p in self._pad],
                rhs_dilation=tuple(self._dilate), dimension_numbers=dn,
                feature_group_count=self._groups,
                preferred_element_type=jnp.int32)
            deq = acc.astype(jnp.float32) * (
                (t_x * self._w_t.reshape(1, -1, 1, 1))
                / (INT8_MAX * INT8_MAX))
            if self._bias is not None:
                deq = deq + self._bias.reshape(1, -1, 1, 1)
            return deq

        out = nd.from_jax(f(raw), ctx=x.context) \
            if hasattr(x, "_read") else f(raw)
        return self._act(out) if self._act is not None else out


# ---------------------------------------------------------------------------
# network conversion
# ---------------------------------------------------------------------------
_QUANTIZABLE = (_nn.Dense, _nn.Conv2D)


def _walk(block, prefix=""):
    for name, child in block._children.items():
        path = prefix + name
        yield path, block, name, child
        yield from _walk(child, path + ".")


def quantize_net(network, quantized_dtype="int8", exclude_layers=None,
                 exclude_layers_match=None, calib_data=None,
                 calib_mode="naive", num_calib_examples=None, ctx=None,
                 logger=None):
    """Quantize a Gluon network in place-of (returns the converted net).

    reference: python/mxnet/contrib/quantization.py (quantize_net). The
    network must have been initialized/forwarded once (shapes known).
    calib_mode: 'none' (dynamic ranges), 'naive' (abs-max), 'entropy'
    (KL-optimal thresholds, calibrate.cc).
    """
    if quantized_dtype != "int8":
        raise NotImplementedError("only int8 quantization is implemented")
    ctx = ctx or cpu()
    log = logger or logging.getLogger(__name__)
    exclude_layers = set(exclude_layers or ())
    exclude_layers_match = list(exclude_layers_match or ())

    targets = {}
    for path, parent, name, child in _walk(network):
        if not isinstance(child, _QUANTIZABLE):
            continue
        if child.name in exclude_layers or path in exclude_layers:
            continue
        if any(m in child.name or m in path for m in exclude_layers_match):
            continue
        if child.weight._data is None:
            raise ValueError(
                "quantize_net: layer %s has uninitialized weights — run a "
                "forward pass first" % child.name)
        targets[path] = (parent, name, child)
    if not targets:
        return network

    thresholds = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise ValueError("calib_mode=%r requires calib_data" % calib_mode)
        collector = _Collector()
        # a hybridized net replays its cached jit program, which would
        # bypass the python-level probes below — run calibration eagerly
        # and restore hybridization afterwards
        hybrid_saved = []
        for _, _, _, blk in _walk(network):
            if isinstance(blk, HybridBlock) and getattr(blk, "_active",
                                                        False):
                hybrid_saved.append(blk)
                blk._active = False
                blk._clear_cached_op()
        if isinstance(network, HybridBlock) and getattr(network, "_active",
                                                        False):
            hybrid_saved.append(network)
            network._active = False
            network._clear_cached_op()
        # temporary forward wrappers record each target layer's INPUT
        originals = {}

        def make_probe(path, child):
            orig = child.forward

            def probe(x, *args, **kw):
                collector.update(path, x.asnumpy())
                return orig(x, *args, **kw)
            return orig, probe

        for path, (parent, name, child) in targets.items():
            orig, probe = make_probe(path, child)
            originals[path] = orig
            child.forward = probe
        if isinstance(calib_data, (nd.NDArray, _np.ndarray)):
            # a bare array is ONE calibration batch — iterating it would
            # feed per-sample (ndim-1) slices into the net
            calib_data = [calib_data]
        try:
            seen = 0
            for batch in calib_data:
                data = batch.data[0] if hasattr(batch, "data") else batch
                if not isinstance(data, nd.NDArray):
                    data = nd.array(data, ctx=ctx)
                network(data)
                seen += data.shape[0]
                if num_calib_examples and seen >= num_calib_examples:
                    break
        finally:
            for path, (parent, name, child) in targets.items():
                child.forward = originals[path]
            for blk in hybrid_saved:
                blk._active = True
                blk._clear_cached_op()
        thresholds = calib_thresholds(collector, calib_mode)
        log.info("quantize_net: calibrated %d layers over %d examples (%s)",
                 len(thresholds), seen, calib_mode)

    for path, (parent, name, child) in targets.items():
        t = thresholds.get(path)
        if isinstance(child, _nn.Conv2D):
            q = QuantizedConv2D(child, t, prefix=child.prefix + "quant_")
        else:
            q = QuantizedDense(child, t, prefix=child.prefix + "quant_")
        parent._children[name] = q
        for attr, val in list(vars(parent).items()):
            if val is child:  # attr-assigned child (e.g. self.fc1)
                object.__setattr__(parent, attr, q)
    # children changed: drop any cached traces so the next call re-traces
    for _, _, _, blk in _walk(network):
        if isinstance(blk, HybridBlock):
            blk._clear_cached_op()
    if isinstance(network, HybridBlock):
        network._clear_cached_op()
    return network


# ---------------------------------------------------------------------------
# symbolic quantization (reference: quantization.py quantize_model — the
# Module-API counterpart of quantize_net: a graph rewrite over NNVM JSON)
# ---------------------------------------------------------------------------
def _json_nodes(symbol):
    import json as _json
    return _json.loads(symbol.tojson())


def _rebuild(graph):
    import json as _json
    from .. import symbol as _sym
    return _sym.load_json(_json.dumps(graph))


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, excluded_op_names=None,
                   calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   quantize_mode="smart", logger=None):
    """Quantize a symbolic model: FullyConnected/Convolution nodes become
    `_contrib_quantize_v2 → _contrib_quantized_* → _contrib_dequantize`
    chains, weights/biases become offline-quantized int8 params with
    scalar range params. Returns (qsym, qarg_params, aux_params).

    reference: python/mxnet/contrib/quantization.py (quantize_model) over
    src/operator/quantization/quantize_graph_pass.cc. Per-tensor symmetric
    ranges, matching the scalar-range contract of the quantized ops here.
    """
    if quantized_dtype != "int8":
        raise NotImplementedError("only int8 quantization is implemented")
    excluded_sym_names = set(excluded_sym_names or ())
    excluded_op_names = set(excluded_op_names or ())
    log = logger or logging.getLogger(__name__)

    graph = _json_nodes(sym)
    nodes = graph["nodes"]
    targets = {}
    for i, n in enumerate(nodes):
        if n["op"] in ("FullyConnected", "Convolution") \
                and n["op"] not in excluded_op_names \
                and n["name"] not in excluded_sym_names:
            wsrc = nodes[n["inputs"][1][0]]
            if wsrc["op"] != "null" or wsrc["name"] not in arg_params:
                continue
            if n["op"] == "Convolution":
                attrs = n.get("attrs", {})
                if attrs.get("layout") not in (None, "None", "NCHW"):
                    continue
            targets[i] = n

    # ---- calibration: per-target INPUT ranges over calib batches -------
    thresholds = {}
    if calib_mode in ("naive", "entropy") and targets:
        if calib_data is None:
            raise ValueError("calib_mode=%r requires calib_data" % calib_mode)
        from .. import symbol as _sym_mod
        internals = sym.get_internals()
        by_name = {s.name: s for s in internals}
        need = {}   # target node idx -> internal symbol of its data input
        for i, n in targets.items():
            src, slot, _ = n["inputs"][0]
            sname = nodes[src]["name"]
            # multi-output internals register ONLY under _output<i> names
            if sname + "_output%d" % slot in by_name:
                sname = sname + "_output%d" % slot
            need[i] = by_name[sname]
        group = _sym_mod.Group(list(need.values()))
        collector = _Collector()
        if isinstance(calib_data, (nd.NDArray, _np.ndarray)):
            calib_data = [calib_data]
        seen = 0
        ex, bound_shape = None, None
        for batch in calib_data:
            data = batch.data[0] if hasattr(batch, "data") else batch
            if isinstance(data, nd.NDArray):
                data = data.asnumpy()
            if ex is None or data.shape != bound_shape:
                ex = group.simple_bind(ctx or cpu(),
                                       **{data_names[0]: data.shape})
                bound_shape = data.shape
                for k, v in arg_params.items():
                    if k in ex.arg_dict:
                        ex.arg_dict[k][:] = v.asnumpy()
                for k, v in (aux_params or {}).items():
                    if k in ex.aux_dict:
                        ex.aux_dict[k][:] = v.asnumpy()
            ex.forward(**{data_names[0]: data})
            for idx, out in zip(need, ex.outputs):
                collector.update(nodes[idx]["name"], out.asnumpy())
            seen += data.shape[0]
            if num_calib_examples and seen >= num_calib_examples:
                break
        thresholds = calib_thresholds(collector, calib_mode)
        log.info("quantize_model: calibrated %d layers over %d examples",
                 len(thresholds), seen)

    # ---- graph rewrite -------------------------------------------------
    qarg = {k: v for k, v in arg_params.items()}
    new_nodes = list(nodes)
    # remap[i] = (node_id, slot) replacing original node i's output 0
    remap = {}

    def _add(node):
        new_nodes.append(node)
        return len(new_nodes) - 1

    def _fix(inp):
        src, slot, x = inp
        if src in remap and slot == 0:
            return [remap[src][0], remap[src][1], x]
        return [src, slot, x]

    quantized_params = {}   # fp32 name -> (node ids) for tied weights

    def _offline_quantize(pname):
        """int8-quantize one fp32 param into qarg + three null nodes;
        reused when several targets share (tie) the same variable."""
        if pname in quantized_params:
            return quantized_params[pname]
        arr = arg_params[pname].asnumpy()
        t = float(max(abs(arr.min()), abs(arr.max()), 1e-30))
        qarg[pname + "_quantize"] = nd.array(
            _np.clip(_np.round(arr * (INT8_MAX / t)), -INT8_MAX, INT8_MAX)
            .astype(_np.int8), dtype="int8")
        qarg[pname + "_quantize_min"] = nd.array(_np.array([-t],
                                                           _np.float32))
        qarg[pname + "_quantize_max"] = nd.array(_np.array([t],
                                                           _np.float32))
        del qarg[pname]
        ids = (_add({"op": "null", "name": pname + "_quantize",
                     "inputs": [],
                     "attrs": {"__shape__": str(tuple(arr.shape)),
                               "__dtype__": "int8"}}),
               _add({"op": "null", "name": pname + "_quantize_min",
                     "inputs": [], "attrs": {"__shape__": "(1,)"}}),
               _add({"op": "null", "name": pname + "_quantize_max",
                     "inputs": [], "attrs": {"__shape__": "(1,)"}}))
        quantized_params[pname] = ids
        return ids

    for i in sorted(targets):
        n = dict(targets[i])
        attrs = dict(n.get("attrs", {}))
        no_bias = str(attrs.get("no_bias", "False")).lower() in ("true", "1")
        wname = new_nodes[n["inputs"][1][0]]["name"]
        wq, wmin, wmax = _offline_quantize(wname)
        if not no_bias and len(n["inputs"]) > 2:
            bname = new_nodes[n["inputs"][2][0]]["name"]
            bq, bmin, bmax = _offline_quantize(bname)
        else:
            bq, bmin, bmax = wq, wmin, wmax  # placeholders, never read
            attrs["no_bias"] = "True"

        qv_attrs = {"out_type": "int8"}
        if n["name"] in thresholds:
            qv_attrs["min_calib_range"] = str(-thresholds[n["name"]])
            qv_attrs["max_calib_range"] = str(thresholds[n["name"]])
        qv = _add({"op": "_contrib_quantize_v2",
                   "name": n["name"] + "_quantize", "attrs": qv_attrs,
                   "inputs": [_fix(n["inputs"][0])]})
        qop = _add({"op": "_contrib_quantized_" +
                    ("fully_connected" if n["op"] == "FullyConnected"
                     else "conv"),
                    "name": n["name"] + "_quantized", "attrs": attrs,
                    "inputs": [[qv, 0, 0], [wq, 0, 0], [bq, 0, 0],
                               [qv, 1, 0], [qv, 2, 0], [wmin, 0, 0],
                               [wmax, 0, 0], [bmin, 0, 0], [bmax, 0, 0]]})
        deq = _add({"op": "_contrib_dequantize",
                    "name": n["name"] + "_dequantize", "attrs": {},
                    "inputs": [[qop, 0, 0], [qop, 1, 0], [qop, 2, 0]]})
        remap[i] = (deq, 0)

    # rewire every consumer (and heads) onto the dequantized outputs
    for j, n in enumerate(new_nodes):
        if n.get("inputs") and j not in (r[0] for r in remap.values()):
            if not (n["name"].endswith("_quantize")
                    or n["name"].endswith("_quantized")
                    or n["name"].endswith("_dequantize")):
                n["inputs"] = [_fix(inp) for inp in n["inputs"]]
    graph["heads"] = [list(_fix(h)) for h in graph["heads"]]

    # the rewrite appended producers after their consumers; NNVM JSON
    # requires topological order — re-sort and renumber
    order, seen = [], set()

    def visit(j):
        if j in seen:
            return
        seen.add(j)
        for src, _, _ in new_nodes[j].get("inputs", []):
            visit(src)
        order.append(j)

    for h in graph["heads"]:
        visit(h[0])
    for j in range(len(new_nodes)):   # keep unreferenced args too
        visit(j)
    renum = {old: new for new, old in enumerate(order)}
    sorted_nodes = []
    for old in order:
        n = dict(new_nodes[old])
        n["inputs"] = [[renum[s], sl, x]
                       for s, sl, x in n.get("inputs", [])]
        sorted_nodes.append(n)
    graph["heads"] = [[renum[h[0]], h[1], h[2]] for h in graph["heads"]]
    graph["nodes"] = sorted_nodes
    graph["arg_nodes"] = [j for j, n in enumerate(sorted_nodes)
                          if n["op"] == "null"]
    graph["node_row_ptr"] = list(range(len(sorted_nodes) + 1))

    qsym = _rebuild(graph)
    log.info("quantize_model: %d layers quantized", len(targets))
    return qsym, qarg, dict(aux_params or {})
