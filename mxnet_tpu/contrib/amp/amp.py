"""AMP implementation. reference: python/mxnet/contrib/amp/amp.py.

The reference rewrites the NNVM graph, inserting `amp_cast`/`amp_multicast`
nodes around ops per the allow/deny lists. The TPU-native version installs a
cast policy at the single imperative dispatch point
(`ndarray.ndarray._invoke`): allow-listed ops (matmul/conv class) get their
floating inputs cast to bf16 (feeding the MXU), deny-listed ops are pinned
to fp32, widest-type ops promote all inputs to the widest present. Casts
happen inside the differentiated/jitted function, so XLA fuses them and
gradients arrive in the parameter's own dtype.

Loss scaling: bf16 shares fp32's exponent range, so scaling is a no-op by
default — but the fp16-style dynamic `LossScaler` is implemented for API
parity (scale_loss / unscale / skip-step-on-overflow semantics).
"""
from __future__ import annotations

import contextlib
import warnings

import numpy as np

from . import lists
from ... import ndarray as nd
from ...ndarray import ndarray as _nd_mod

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_model",
           "LossScaler", "list_lp16_ops", "list_fp32_ops"]

_initialized = False
_target_dtype = None


def list_lp16_ops(target_dtype=None):
    return list(lists.TARGET_DTYPE_OPS)


def list_fp32_ops(target_dtype=None):
    return list(lists.FP32_OPS)


def _is_float(raw):
    dt = getattr(raw, "dtype", None)
    if dt is None:
        return False  # python scalars pass through untouched
    if str(dt) == "bfloat16":
        return True
    try:
        return np.dtype(dt).kind == "f"
    except TypeError:
        return False


def _make_policy(target_dtype):
    import jax.numpy as jnp

    target = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}[target_dtype]
    target_ops = set(lists.TARGET_DTYPE_OPS)
    fp32_ops = set(lists.FP32_OPS)
    widest_ops = set(lists.WIDEST_TYPE_CASTS)
    cache = {}

    def wrap(fn, op_name):
        key = (op_name, fn)
        hit = cache.get(key)
        if hit is None:
            hit = cache[key] = _wrap_uncached(fn, op_name)
        return hit

    def _wrap_uncached(fn, op_name):
        if op_name in target_ops:
            def cast_fn(*args, **kw):
                args = [a.astype(target) if _is_float(a) else a
                        for a in args]
                return fn(*args, **kw)
            return cast_fn
        if op_name in fp32_ops:
            def cast_fn(*args, **kw):
                args = [a.astype(jnp.float32) if _is_float(a) and
                        a.dtype != jnp.float64 else a for a in args]
                return fn(*args, **kw)
            return cast_fn
        if op_name in widest_ops:
            def cast_fn(*args, **kw):
                fl = [a for a in args if _is_float(a)]
                if len(fl) > 1:
                    widest = jnp.result_type(*[a.dtype for a in fl])
                    args = [a.astype(widest) if _is_float(a) else a
                            for a in args]
                return fn(*args, **kw)
            return cast_fn
        return fn

    return wrap


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP. reference: amp.py (init). On TPU the default (and
    recommended) target is bfloat16; float16 is accepted for parity."""
    global _initialized, _target_dtype
    if target_dtype in (np.float16, "float16", "fp16"):
        target_dtype = "float16"
    elif target_dtype in ("bfloat16", "bf16"):
        target_dtype = "bfloat16"
    else:
        raise ValueError(
            "unsupported AMP target_dtype %r: expected 'bfloat16' or "
            "'float16'" % (target_dtype,))
    if _initialized:
        warnings.warn("amp.init() is already called, ignoring.")
        return
    if target_precision_ops:
        lists.TARGET_DTYPE_OPS.extend(target_precision_ops)
    if fp32_ops:
        lists.FP32_OPS.extend(fp32_ops)
    _initialized = True
    _target_dtype = target_dtype
    _nd_mod._AMP_WRAP = _make_policy(target_dtype)


class LossScaler:
    """Dynamic loss scaler. reference: amp/loss_scaler.py — double on
    `scale_window` clean steps, halve on overflow, skip the update that
    overflowed."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any grad is non-finite.

        Fused: ONE on-device all-finite reduction across every grad and a
        single host sync, instead of a per-grad asnumpy() round-trip —
        the decision is bit-identical (isfinite is exact in every float
        dtype, so reducing on device changes nothing), and the n-1
        avoided syncs are counted as ``amp.syncs_saved``. An overflow is
        also noted to the integrity sentinel
        (``integrity.amp_overflow``) so telemetry can tell an AMP
        overflow skip from a divergence rollback."""
        from ... import telemetry as _telem
        from ...resilience import integrity as _integrity
        raws = [g._read() for p in params if p.grad_req != "null"
                for g in p.list_grad()]
        if not raws:
            return False
        overflow = not bool(_integrity.finite_scalar(raws))
        if len(raws) > 1:
            _telem.inc("amp.syncs_saved", len(raws) - 1)
        if overflow:
            _integrity.note_amp_overflow()
        return overflow

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


def init_trainer(trainer):
    """Attach a LossScaler and overflow-skip logic to a Gluon Trainer.
    reference: amp.py (init_trainer)."""
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return
    scaler = LossScaler() if _target_dtype == "float16" else \
        LossScaler(init_scale=1.0, scale_factor=1.0)
    trainer._amp_loss_scaler = scaler
    trainer._amp_unscaled = False
    orig_update = trainer._update

    def patched_update(ignore_stale_grad=False):
        scale = scaler.loss_scale
        if scale != 1.0 and not trainer._amp_unscaled:
            for p in trainer._params:
                if p.grad_req == "null":
                    continue
                for g in p.list_grad():
                    g[:] = g / scale
        trainer._amp_unscaled = False
        overflow = scaler.has_overflow(trainer._params) \
            if _target_dtype == "float16" else False
        scaler.update_scale(overflow)
        if overflow:
            from ...resilience import integrity as _integrity
            _integrity.note_amp_skip()
            return  # skip this update
        orig_update(ignore_stale_grad)

    trainer._update = patched_update


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """reference: amp.py (scale_loss). Usage::

        with amp.scale_loss(loss, trainer) as scaled:
            autograd.backward(scaled)
    """
    if getattr(trainer, "_amp_loss_scaler", None) is None:
        init_trainer(trainer)
    scale = trainer._amp_loss_scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield [l * scale for l in loss]
    else:
        yield loss * scale


def unscale(trainer):
    """Divide grads by the current loss scale (for manual clip-then-step).
    reference: amp.py (unscale). The next trainer.step() skips its own
    unscale for this one update; the scaler's loss_scale is untouched."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            g[:] = g / scaler.loss_scale
    trainer._amp_unscaled = True


def convert_model(net, target_dtype="bfloat16"):
    """Cast a Gluon block's parameters to the target dtype (the inference
    analog of graph conversion; reference amp.convert_model converts a
    symbol+params pair)."""
    import jax.numpy as jnp
    target = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}[target_dtype]
    for p in net.collect_params().values():
        if p._data is None:
            p.dtype = target_dtype
            continue
        for ctx in list(p._data.keys()):
            arr = p._data[ctx]
            if _is_float(arr._read()):
                arr._write(arr._read().astype(target))
    return net
