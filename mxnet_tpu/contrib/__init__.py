"""contrib namespace. reference: python/mxnet/contrib/ — AMP now;
quantization/onnx are documented out-of-scope for the TPU build
(SURVEY.md §2.1)."""
from . import amp

__all__ = ["amp"]
