"""contrib namespace. reference: python/mxnet/contrib/ — AMP,
INT8 quantization, text (vocab/embeddings), ONNX export/import."""
from . import amp
from . import quantization
from . import text
from . import onnx
from . import svrg_optimization
from . import tensorboard

__all__ = ["amp", "quantization", "text", "onnx", "svrg_optimization",
           "tensorboard"]
