"""contrib namespace. reference: python/mxnet/contrib/ — AMP,
INT8 quantization, text (vocab/embeddings); onnx remains documented
out-of-scope (SURVEY.md §2.1)."""
from . import amp
from . import quantization
from . import text

__all__ = ["amp", "quantization", "text"]
