"""contrib namespace. reference: python/mxnet/contrib/ — AMP +
INT8 quantization; onnx remains documented out-of-scope (SURVEY.md §2.1)."""
from . import amp
from . import quantization

__all__ = ["amp", "quantization"]
