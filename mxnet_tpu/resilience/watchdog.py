"""Hang watchdog: deadlines and heartbeats around dispatched steps.

The worst fleet failure mode is not a crash — it is a collective that never
completes. One peer is gone, every other worker blocks inside the dispatch,
and the run sits silent until an operator notices. The watchdog replaces
that silence with a structured `StallError`:

    wd = watchdog.get()
    with wd.guard("train.step", deadline_s=30):
        loss = step(params, batch)          # raises StallError if > 30 s

A single daemon monitor thread tracks every armed guard (one per guarded
thread). When a deadline passes it:

1. increments ``resilience.stalls`` (+ per-site counter),
2. snapshots the post-mortem — the telemetry span tail (host story), the
   per-device PjRt state (live buffer counts/bytes + allocator watermarks,
   probed while the thread is still stuck in the op), and the
   last-compiled executables; `StallError.format_report()` renders all of
   it as one structured dump,
3. raises `StallError` *asynchronously inside the guarded thread* via
   ``PyThreadState_SetAsyncExc``, and
4. invokes the guard's ``on_stall`` callback (fleet integration point:
   page someone, dump a trace file, start draining).

The async raise lands at the next Python bytecode boundary — it interrupts
Python-level waits (including the cooperative hangs `resilience.faults`
injects, which sleep in small ticks for exactly this reason) but cannot
interrupt a thread blocked inside a C call; for that case the stall is
still *recorded* and `guard.__exit__` re-checks, so the error surfaces the
moment the call returns instead of being silently swallowed.

``heartbeat()`` re-arms the current thread's deadline — long steps that are
alive (e.g. per-microbatch progress) call it to say "still moving".

Default deadline: ``MXNET_TPU_STEP_DEADLINE_S`` (unset = no default; a
guard without any deadline is a no-op).
"""
from __future__ import annotations

import ctypes
import os
import threading
import time

from .errors import StallError

__all__ = ["Watchdog", "get", "guard", "heartbeat", "default_deadline_s"]


def default_deadline_s():
    val = os.environ.get("MXNET_TPU_STEP_DEADLINE_S")
    if not val:
        return None
    try:
        return float(val)
    except ValueError:
        return None


def _async_raise(tid, exctype):
    """Raise `exctype` in thread `tid` at its next bytecode boundary."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exctype))
    if res > 1:  # pragma: no cover — "we broke more than one thread state"
        ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)


def _async_clear(tid):
    ctypes.pythonapi.PyThreadState_SetAsyncExc(ctypes.c_ulong(tid), None)


def _probe_devices(timeout_s=2.0):
    """telemetry.device_report() under a hard timeout: the probe rides a
    throwaway daemon thread and is abandoned (empty dump) if the PjRt
    runtime is too wedged to answer — the caller (the watchdog monitor)
    must never block on it."""
    from .. import telemetry as _telem
    result = []

    def probe():
        try:
            result.extend(_telem.device_report())
        except Exception:  # noqa: BLE001 - post-mortem is best-effort
            pass

    t = threading.Thread(target=probe, name="mxnet_tpu_device_probe",
                         daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return []  # abandoned; the daemon thread dies with the process
    return list(result)


class _AsyncStall(BaseException):
    """Carrier raised asynchronously in the stalled thread; `_Guard.__exit__`
    converts it into the rich `StallError` (SetAsyncExc only accepts a
    class, so the payload travels on the guard entry instead).

    BaseException so a guarded ``except Exception`` retry loop inside the
    stalled region cannot accidentally swallow the interruption."""


class _Entry:
    __slots__ = ("site", "deadline", "deadline_s", "on_stall", "fired",
                 "stall")

    def __init__(self, site, deadline, deadline_s, on_stall):
        self.site = site
        self.deadline = deadline          # absolute monotonic time
        self.deadline_s = deadline_s      # the span, for messages
        self.on_stall = on_stall
        self.fired = False
        self.stall = None                 # prepared StallError


class Watchdog:
    """Monitor thread + per-thread guard registry."""

    def __init__(self, poll_floor_s=0.005):
        from ..analysis import lockguard
        self._entries = {}  # thread ident -> _Entry
        self._cond = lockguard.condition("resilience.watchdog")
        self._thread = None
        self._poll_floor_s = poll_floor_s

    # ------------------------------------------------------------- monitor
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="mxnet_tpu_watchdog", daemon=True)
            self._thread.start()

    def _loop(self):
        while True:
            with self._cond:
                if not self._entries:
                    # park until the next guard arms
                    self._cond.wait()
                    continue
                now = time.monotonic()
                pending = [e.deadline for e in self._entries.values()
                           if not e.fired]
                if not pending:
                    self._cond.wait()
                    continue
                next_deadline = min(pending)
                if next_deadline > now:
                    self._cond.wait(max(self._poll_floor_s,
                                        next_deadline - now))
                    continue
                expired = [(tid, e) for tid, e in self._entries.items()
                           if not e.fired and e.deadline <= now]
                for _, entry in expired:
                    entry.fired = True  # claim under the lock: fire once
            # fire OUTSIDE the lock — building the span dump and (above
            # all) the user on_stall callback must not block heartbeat(),
            # _disarm(), or other threads' deadlines
            for tid, entry in expired:
                self._fire(tid, entry)

    def _fire(self, tid, entry):
        """Called without the lock; entry.fired was claimed under it."""
        from .. import telemetry as _telem
        # the device-side half of the post-mortem: per-device live-buffer
        # counts/bytes + allocator stats, and the executables most recently
        # handed to the device. Probed while the stalled thread is still
        # stuck inside the op — this IS the state of the hang, not of the
        # cleanup after it. The probe runs on ITS OWN bounded thread: a
        # runtime wedged hard enough to block memory_stats() must not hang
        # the single monitor thread (that would silence every other
        # guard's deadline — the watchdog hanging is the one unacceptable
        # failure mode).
        device_dump = _probe_devices(timeout_s=2.0)
        stall = StallError(
            "watchdog: %r exceeded its %.3gs deadline (no heartbeat) — "
            "raising instead of hanging forever"
            % (entry.site, entry.deadline_s),
            site=entry.site, deadline_s=entry.deadline_s,
            span_dump=_telem.span_events(limit=64),
            device_dump=device_dump,
            compile_dump=_telem.recent_compiles(limit=10),
            flight_dump=_telem.flight_records(limit=32),
            ledger_dump=_telem.memory_scopes())
        with self._cond:
            if self._entries.get(tid) is not entry:
                # the op completed between deadline-claim and now: its guard
                # saw stall=None and exited clean — do NOT raise into
                # whatever that thread is running next
                return
            entry.stall = stall
            _async_raise(tid, _AsyncStall)
        _telem.inc("resilience.stalls")
        _telem.inc("resilience.stalls.%s" % entry.site)
        _telem.record_span("stall@%s" % entry.site, "resilience",
                           _telem.span_clock(), 0.0)
        if entry.on_stall is not None:
            try:
                entry.on_stall(stall)
            except Exception:  # noqa: BLE001 — callbacks must not kill us
                pass

    # -------------------------------------------------------------- guards
    def guard(self, site, deadline_s=None, on_stall=None):
        """Context manager arming a deadline for the calling thread."""
        return _Guard(self, site, deadline_s, on_stall)

    def heartbeat(self):
        """Push the current thread's armed deadline forward by its full
        span — "alive, keep waiting"."""
        tid = threading.get_ident()
        with self._cond:
            entry = self._entries.get(tid)
            if entry is not None and not entry.fired:
                entry.deadline = time.monotonic() + entry.deadline_s
                self._cond.notify_all()

    def _arm(self, entry):
        tid = threading.get_ident()
        with self._cond:
            if tid in self._entries:
                raise RuntimeError(
                    "watchdog guard already armed for this thread "
                    "(site=%r); nested guards are not supported"
                    % self._entries[tid].site)
            self._entries[tid] = entry
            self._ensure_thread()
            self._cond.notify_all()
        return tid

    def _disarm(self, tid):
        """Remove the thread's entry; returns the prepared StallError if the
        async raise was actually sent (and clears it if still undelivered).
        entry.fired with stall=None means the deadline was claimed but the
        op completed before _fire re-checked — no exception was or will be
        sent (the _fire registration re-check), so that is a clean exit."""
        with self._cond:
            entry = self._entries.pop(tid, None)
            stall = entry.stall if entry is not None else None
            self._cond.notify_all()
        if stall is not None:
            _async_clear(tid)
        return stall


class _Guard:
    def __init__(self, wd, site, deadline_s, on_stall):
        self._wd = wd
        self._site = site
        if deadline_s is None:
            deadline_s = default_deadline_s()
        self._deadline_s = deadline_s
        self._on_stall = on_stall
        self._tid = None

    def __enter__(self):
        if self._deadline_s is None:
            return self  # no deadline configured: transparent
        entry = _Entry(self._site, time.monotonic() + self._deadline_s,
                       self._deadline_s, self._on_stall)
        self._tid = self._wd._arm(entry)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._tid is None:
            return False
        try:
            stall = self._wd._disarm(self._tid)
        except _AsyncStall:
            # the carrier landed INSIDE __exit__ (op completed a hair after
            # the raise was sent, before _disarm could clear it) — the
            # retry returns the prepared StallError for the normal path
            stall = self._wd._disarm(self._tid)
        if stall is not None:
            # the deadline fired and the async carrier was sent: surface
            # the rich StallError whether the carrier landed (exc_type is
            # _AsyncStall), the op raised something else while dying, or
            # the carrier was cleared undelivered just above.
            if exc is not None and not isinstance(exc, _AsyncStall):
                raise stall from exc
            raise stall
        if isinstance(exc, _AsyncStall):
            # carrier without a recorded stall should be impossible; never
            # let the bare internal BaseException escape regardless
            raise StallError(
                "watchdog: %r interrupted (stall record lost)" % self._site,
                site=self._site, deadline_s=self._deadline_s) from exc
        return False


# ------------------------------------------------------------- module-level
_DEFAULT = Watchdog()


def get():
    return _DEFAULT


def guard(site, deadline_s=None, on_stall=None):
    return _DEFAULT.guard(site, deadline_s=deadline_s, on_stall=on_stall)


def heartbeat():
    _DEFAULT.heartbeat()
