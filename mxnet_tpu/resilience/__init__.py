"""mxnet_tpu.resilience — fault-tolerant training.

On real TPU fleets the dominant failure modes are preempted hosts, hung
collectives, and flaky dist-kvstore endpoints; without this layer a single
fault kills the whole run. The subsystem has four parts, each usable alone:

``faults``    deterministic fault injection (env ``MXNET_TPU_FAULT_PLAN`` or
              ``faults.inject(...)``) into kvstore push/pull, collective
              dispatch, and train-step sites — every recovery path below is
              testable on one chip;
``retry``     exponential-backoff retry engine with jitter, per-op
              deadlines, and transient-vs-fatal error classification —
              wired into `kvstore_dist`, eager collectives, and
              `dist.initialize` (knob: ``MXNET_TPU_RETRIES``);
``watchdog``  heartbeat monitor that turns a hung step/collective into a
              structured `StallError` (with a telemetry span dump) instead
              of silence (knob: ``MXNET_TPU_STEP_DEADLINE_S``);
``run``       `ResilientRunner` — periodic atomic checkpoints, catch
              retriable faults, restore ``latest_step`` and replay, with a
              max-restart budget and automatic elastic re-layout (rebuild
              the step + re-shard the state) when the device set shrinks
              or grows back;
``commit``    `CommitCoordinator` — two-phase coordinated commit for pod
              runs: payload first, fleet-wide min-step election over the
              jax.distributed coordinator, THEN the LATEST marker — every
              rank restores the same elected step even after a
              mid-commit crash;
``preempt``   `PreemptionListener` — SIGTERM + maintenance-event poller
              (``MXNET_TPU_PREEMPT_POLL_S``) turned into proactive
              checkpoints: resume replays zero steps instead of a
              ckpt_every window;
``integrity`` the divergence sentinel (``MXNET_TPU_INTEGRITY=1``): an
              all-finite check fused into the comm-bucket / fused-step
              programs plus a rolling-median loss-spike detector
              (``MXNET_TPU_LOSS_SPIKE_FACTOR``) — both raise a structured
              `DivergenceError` that `ResilientRunner` answers with
              rollback-to-last-good + skip-the-poisoned-batch (budget:
              ``MXNET_TPU_ROLLBACK_BUDGET``). Checkpoints carry sha256
              payload checksums; a corrupt snapshot falls back to the
              next-oldest instead of crashing.

Everything reports through `mx.telemetry`: ``resilience.faults_injected`` /
``retries`` / ``stalls`` / ``restores`` / ``checkpoints`` /
``proactive_checkpoints`` / ``mesh_shrinks`` / ``mesh_grows`` /
``commit.elections`` / ``preempt.notices`` counters plus chrome-trace
spans for backoffs, checkpoints, restores, and stalls.

Quick start::

    import mxnet_tpu as mx
    from mxnet_tpu import resilience

    runner = resilience.ResilientRunner.for_fused_step(
        fused_step, batch_fn, ckpt_dir="/tmp/ckpt", ckpt_every=50,
        max_restarts=3, step_deadline_s=120)
    report = runner.run(num_steps)
"""
from . import (errors, faults, retry, watchdog, run, commit,  # noqa: F401
               preempt, integrity)

from .errors import (ResilienceError, RetriableError, TransportError,  # noqa: F401
                     InjectedFault, PreemptionError, StallError,
                     DivergenceError, RetryExhausted, FatalTrainingError,
                     CheckpointCorruptError, classify, is_retriable)
from .faults import FaultPlan, FaultSpec, inject  # noqa: F401
from .retry import RetryPolicy, call_with_retry, retriable  # noqa: F401
from .run import ResilientRunner, RunReport, SnapshotCheckpointer  # noqa: F401
from .watchdog import Watchdog, guard, heartbeat  # noqa: F401
from .commit import CommitCoordinator, elect_step  # noqa: F401
from .preempt import PreemptionListener, PreemptionNotice  # noqa: F401

__all__ = ["errors", "faults", "retry", "watchdog", "run", "commit",
           "preempt", "integrity",
           "ResilienceError", "RetriableError", "TransportError",
           "InjectedFault", "PreemptionError", "StallError",
           "DivergenceError", "RetryExhausted", "FatalTrainingError",
           "CheckpointCorruptError", "classify",
           "is_retriable", "FaultPlan", "FaultSpec", "inject",
           "RetryPolicy", "call_with_retry", "retriable",
           "ResilientRunner", "RunReport", "SnapshotCheckpointer",
           "Watchdog", "guard", "heartbeat",
           "CommitCoordinator", "elect_step",
           "PreemptionListener", "PreemptionNotice"]
