"""Retry policy engine: exponential backoff + jitter + per-op deadlines.

The comm layers (`kvstore_dist`, eager collectives, `dist.initialize`) wrap
their dispatch in `call_with_retry`, so a flaky endpoint costs a backoff
sleep instead of the whole run. Error classification is delegated to
`resilience.errors.classify` — deterministic failures (shape/dtype, key not
initialized) are re-raised on the first attempt; only transient transport
faults burn retry budget.

Env knobs (read per-call so tests can flip them):

``MXNET_TPU_RETRIES``        max attempts per op (default 3; 1 = no retry)
``MXNET_TPU_RETRY_BASE_S``   first backoff delay (default 0.05 s)
``MXNET_TPU_RETRY_MAX_S``    backoff ceiling (default 2 s)

Telemetry: every retried attempt increments ``resilience.retries`` (and
``resilience.retries.<site>``); exhaustion increments
``resilience.retry_exhausted`` and raises `RetryExhausted` carrying the
site, attempt count, and last cause.
"""
from __future__ import annotations

import os
import random as _pyrandom
import time

from .errors import DivergenceError, RetryExhausted, classify

__all__ = ["RetryPolicy", "call_with_retry", "retriable", "default_policy"]


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class RetryPolicy:
    """How many times, how long apart, and for how long in total.

    jitter: each delay is multiplied by a uniform draw from
    [1-jitter, 1+jitter] so synchronized workers don't stampede a
    recovering endpoint in lockstep.
    deadline_s: wall-clock budget across ALL attempts of one op; when the
    next backoff would cross it, the policy gives up early.
    """

    def __init__(self, max_attempts=None, base_delay_s=None, max_delay_s=None,
                 jitter=0.25, deadline_s=None):
        if max_attempts is None:
            max_attempts = int(_env_float("MXNET_TPU_RETRIES", 3))
        if base_delay_s is None:
            base_delay_s = _env_float("MXNET_TPU_RETRY_BASE_S", 0.05)
        if max_delay_s is None:
            max_delay_s = _env_float("MXNET_TPU_RETRY_MAX_S", 2.0)
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s

    def delay(self, attempt):
        """Backoff before attempt number `attempt+1` (attempt is 1-based:
        delay(1) runs after the first failure)."""
        d = min(self.max_delay_s, self.base_delay_s * (2.0 ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _pyrandom.random() - 1.0)
        return max(0.0, d)

    def __repr__(self):
        return ("RetryPolicy(max_attempts=%d, base=%gs, max=%gs, "
                "jitter=%g, deadline=%s)"
                % (self.max_attempts, self.base_delay_s, self.max_delay_s,
                   self.jitter, self.deadline_s))


def default_policy():
    return RetryPolicy()


def call_with_retry(fn, *args, site="op", policy=None, context=None,
                    on_retry=None, retry_on=None, **kwargs):
    """Run ``fn(*args, **kwargs)``, retrying transient failures.

    site: telemetry/diagnostic label for this call site.
    context: short string folded into error messages (e.g. "key=3 shard=(4,)").
    on_retry: optional callback ``(attempt, exc)`` before each backoff sleep.
    retry_on: predicate narrowing WHICH retriable errors retry in place —
        e.g. a runner passes ``lambda e: isinstance(e, TransportError)`` so
        preemptions/stalls propagate to its restore-and-replay path instead
        of burning in-place attempts.

    Fatal errors (per `errors.classify`) propagate immediately. Transient
    errors are retried up to ``policy.max_attempts`` within
    ``policy.deadline_s``; then `RetryExhausted` chains the last cause.
    """
    from .. import telemetry as _telem
    if policy is None:
        policy = RetryPolicy()
    t0 = time.monotonic()
    last = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — classifier decides
            if isinstance(exc, DivergenceError):
                # deterministic at retry granularity: the same inputs
                # diverge again — only the runner's rollback-and-skip can
                # absorb it, so it must surface unmasked
                raise
            if classify(exc) != "retriable":
                raise
            if retry_on is not None and not retry_on(exc):
                raise
            last = exc
            if attempt >= policy.max_attempts:
                break
            delay = policy.delay(attempt)
            if (policy.deadline_s is not None
                    and time.monotonic() + delay - t0 > policy.deadline_s):
                break
            _telem.inc("resilience.retries")
            _telem.inc("resilience.retries.%s" % site)
            if on_retry is not None:
                on_retry(attempt, exc)
            with _telem.span("retry_backoff@%s" % site, "resilience"):
                time.sleep(delay)
    _telem.inc("resilience.retry_exhausted")
    detail = (" [%s]" % context) if context else ""
    raise RetryExhausted(
        "%s%s failed after %d attempt(s) in %.2fs; last error: %s: %s"
        % (site, detail, min(policy.max_attempts, attempt),
           time.monotonic() - t0, type(last).__name__, last),
        site=site, attempts=attempt, last_error=last) from last


def retriable(site="op", policy=None):
    """Decorator form of `call_with_retry`. site/policy bind at decoration
    time; every call arg/kwarg reaches the wrapped function untouched
    (including ones named like call_with_retry's own parameters)."""
    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return call_with_retry(lambda: fn(*args, **kwargs),
                                   site=site, policy=policy)
        return inner
    return wrap
