"""Structured error taxonomy for fault-tolerant training.

On a real TPU fleet the failures that kill a run are rarely bugs in the
model: they are preempted hosts, flaky dist-kvstore endpoints, and hung
collectives. Recovering from them safely requires telling *transient
transport* faults (worth retrying, worth restoring a checkpoint for) apart
from *deterministic* errors (wrong shape/dtype/key — retrying replays the
same crash forever). This module is that classifier plus the exception
types every resilience component raises.

Hierarchy::

    MXNetError
      ResilienceError                  base of everything raised here
        RetriableError                 safe to retry / restore-and-replay
          TransportError               flaky comm endpoint, reset conn, ...
            InjectedFault              raised by resilience.faults (testing)
          PreemptionError              host/device preemption notice
          StallError                   watchdog deadline passed (span dump)
          DivergenceError              numeric divergence (non-finite grads
                                       / loss spike) — recovery is
                                       ROLLBACK-to-last-good + skip the
                                       poisoned batch, never an in-place
                                       retry (the same batch diverges again)
          RetryExhausted               retries spent; carries the last cause
        FatalTrainingError             deterministic — do NOT retry
          CheckpointCorruptError       every on-disk snapshot failed its
                                       checksum — nothing left to restore

`classify(exc)` maps arbitrary exceptions (including jaxlib's
XlaRuntimeError grpc-flavored messages) onto "retriable" / "fatal".
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["ResilienceError", "RetriableError", "TransportError",
           "InjectedFault", "PreemptionError", "StallError",
           "DivergenceError", "RetryExhausted", "FatalTrainingError",
           "CheckpointCorruptError", "classify", "is_retriable"]


class ResilienceError(MXNetError):
    """Base class of every error raised by mxnet_tpu.resilience."""


class RetriableError(ResilienceError):
    """A fault where retrying (or restoring a snapshot and replaying) can
    succeed: nothing about the program itself is wrong."""


class TransportError(RetriableError):
    """Flaky communication: reset connections, unreachable endpoints,
    transient collective failures. The dist-kvstore analog of ps-lite's
    ZMQ send/recv errors."""

    def __init__(self, message, site=None, key=None, attempt=None):
        super().__init__(message)
        self.site = site
        self.key = key
        self.attempt = attempt


class InjectedFault(TransportError):
    """A deterministic fault planted by `resilience.faults` so recovery
    paths are testable on one chip. Behaves exactly like a TransportError."""


class PreemptionError(RetriableError):
    """The host (or part of the device set) is going away — the simulated
    analog of a TPU-VM maintenance preemption. Recovery is
    restore-from-checkpoint, not an in-place retry."""


class StallError(RetriableError):
    """A watched operation failed to heartbeat before its deadline.

    Raised by `resilience.watchdog` *instead of hanging forever* — the
    structured replacement for a run that sits in a dead collective until
    an operator kills it. Carries the site, the deadline, and a full
    post-mortem: the most recent telemetry spans (host-side story), the
    per-device PjRt state (live buffer counts/bytes, allocator watermarks
    — the device-side story), the last-compiled executables (what was
    most recently handed to the device), and the flight-recorder ring
    (the last N steps' ledger — what the run was DOING when it died).
    `format_report()` renders all of it as one structured dump.
    """

    def __init__(self, message, site=None, deadline_s=None, span_dump=None,
                 device_dump=None, compile_dump=None, flight_dump=None,
                 ledger_dump=None):
        super().__init__(message)
        self.site = site
        self.deadline_s = deadline_s
        # list of (name, cat, ts_s, dur_s, tid) — telemetry.span_events tail
        self.span_dump = list(span_dump or [])
        # list of per-device dicts — telemetry.device_report()
        self.device_dump = list(device_dump or [])
        # list of (executable_name, ts_s) — telemetry.recent_compiles()
        self.compile_dump = list(compile_dump or [])
        # list of per-step dicts — telemetry.flight_records() tail
        self.flight_dump = list(flight_dump or [])
        # {scope: bytes} — telemetry.memory_scopes() (the HBM ledger):
        # WHOSE bytes the device held when it hung
        self.ledger_dump = dict(ledger_dump or {})

    def format_spans(self, limit=20):
        lines = ["recent spans (newest last):"]
        for name, cat, ts_s, dur_s, _tid in self.span_dump[-limit:]:
            lines.append("  %10.3fs %-8s %s (%.3f ms)"
                         % (ts_s, cat, name, dur_s * 1e3))
        return "\n".join(lines)

    def format_devices(self):
        if not self.device_dump:
            return "device state: unavailable"
        lines = ["device state:"]
        for entry in self.device_dump:
            parts = ["  %-8s" % entry.get("device", "?")]
            for key in ("live_buffers", "live_bytes", "bytes_in_use",
                        "peak_bytes_in_use", "num_allocs"):
                if key in entry:
                    parts.append("%s=%s" % (key, entry[key]))
            lines.append(" ".join(parts))
        return "\n".join(lines)

    def format_flight(self, limit=10):
        from ..telemetry.flight import format_records
        return format_records(self.flight_dump, limit=limit)

    def format_ledger(self, top=6):
        """Per-scope HBM breakdown, largest first — the memory half of
        "what was the device holding when it hung"."""
        if not self.ledger_dump:
            return "memory ledger: unavailable"
        lines = ["memory ledger (top scopes):"]
        ranked = sorted(self.ledger_dump.items(),
                        key=lambda kv: -abs(kv[1]))
        for name, val in ranked[:top]:
            lines.append("  %-14s %d bytes" % (name, val))
        return "\n".join(lines)

    def format_report(self, span_limit=20):
        """The one-stop post-mortem: host spans, device state, the HBM
        ledger's scope breakdown, the last-compiled executables, and the
        flight-recorder step ledger."""
        lines = [str(self), "", self.format_spans(limit=span_limit), "",
                 self.format_devices()]
        if self.ledger_dump:
            lines.append(self.format_ledger())
        if self.compile_dump:
            lines.append("last compiled executables (newest last):")
            for name, ts_s in self.compile_dump[-10:]:
                lines.append("  %10.3fs %s" % (ts_s, name))
        lines.append("")
        lines.append(self.format_flight())
        return "\n".join(lines)


class DivergenceError(RetriableError):
    """The integrity sentinel tripped: a non-finite value rode a gradient
    bucket, a fused step produced NaN/Inf, or the loss spiked past the
    rolling-median divergence factor.

    Classified transient-WITH-ROLLBACK: retrying the same step in place
    replays the identical divergence (the poisoned batch is deterministic),
    so `ResilientRunner` restores the last *committed* snapshot and advances
    the data stream past the poisoned batch window instead. Carries the
    offending step (when the runner set one), the sentinel site, the
    bucket/param keys that tripped, and the flight-recorder ring tail —
    the post-mortem a silent-corruption incident needs.
    """

    def __init__(self, message, step=None, site=None, keys=None,
                 flight_dump=None):
        super().__init__(message)
        self.step = step
        self.site = site
        self.keys = list(keys or [])
        # list of per-step dicts — telemetry.flight_records() tail
        self.flight_dump = list(flight_dump or [])

    def format_flight(self, limit=10):
        from ..telemetry.flight import format_records
        return format_records(self.flight_dump, limit=limit)

    def format_report(self):
        lines = [str(self)]
        if self.keys:
            lines.append("offending keys: %s" % ",".join(
                str(k) for k in self.keys))
        lines.append("")
        lines.append(self.format_flight())
        return "\n".join(lines)


class RetryExhausted(RetriableError):
    """Every attempt a RetryPolicy allowed failed with a retriable error.
    Carries the last underlying cause; still retriable at a coarser
    granularity (a runner may restore a checkpoint and replay)."""

    def __init__(self, message, site=None, attempts=None, last_error=None):
        super().__init__(message)
        self.site = site
        self.attempts = attempts
        self.last_error = last_error


class FatalTrainingError(ResilienceError):
    """Deterministic failure (shape/dtype mismatch, uninitialized key,
    programming error). Retrying replays the identical crash — surface it
    immediately instead."""


class CheckpointCorruptError(FatalTrainingError):
    """Every candidate snapshot failed its sha256 verification (or could
    not be unpickled). A single corrupt payload is RECOVERABLE — the
    checkpointer falls back to the next-oldest keep=N snapshot and counts
    ``checkpoint.corrupt`` — so reaching this error means the whole
    retention window is bad: surface it, do not spin."""

    def __init__(self, message, steps_tried=None):
        super().__init__(message)
        self.steps_tried = list(steps_tried or [])


# ---------------------------------------------------------------- classifier
# Substrings that mark a low-level runtime error as transient transport
# trouble. Sources: grpc status names surfaced by jaxlib's XlaRuntimeError,
# the distributed-runtime coordinator, and plain socket errors.
_TRANSIENT_MARKERS = (
    "unavailable", "deadline_exceeded", "deadline exceeded",
    "connection reset", "connection refused", "connection closed",
    "broken pipe", "socket closed", "timed out", "timeout",
    "preempted", "cancelled", "aborted", "heartbeat",
    "failed to connect", "coordination service",
)

# Deterministic-programming-error markers: never retriable even when they
# arrive wrapped in a runtime error type.
_FATAL_MARKERS = (
    "shape", "dtype", "rank mismatch", "invalid_argument",
    "invalid argument", "not been initialized", "unimplemented",
    "out of memory", "resource_exhausted", "resource exhausted",
)


def classify(exc):
    """Map an exception to "retriable" or "fatal".

    Explicit resilience types carry their own verdict; everything else is
    judged by type and message. Unknown errors default to "fatal" — silently
    retrying an unclassified crash hides bugs.
    """
    if isinstance(exc, RetriableError):
        return "retriable"
    if isinstance(exc, FatalTrainingError):
        return "fatal"
    if isinstance(exc, (ConnectionError, BrokenPipeError, TimeoutError,
                        InterruptedError)):
        return "retriable"
    if isinstance(exc, (TypeError, ValueError, KeyError, IndexError,
                        AssertionError, NotImplementedError,
                        ZeroDivisionError, AttributeError)):
        return "fatal"
    msg = str(exc).lower()
    # fatal markers win: "invalid argument: connection metadata" should not
    # spin in a retry loop
    if any(m in msg for m in _FATAL_MARKERS):
        return "fatal"
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return "retriable"
    return "fatal"


def is_retriable(exc):
    return classify(exc) == "retriable"
