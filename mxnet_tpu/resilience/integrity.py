"""Training integrity sentinel: catch silent divergence before it spreads.

The resilience layers recover from faults that *raise*. The nastiest fleet
failures are silent: one non-finite gradient poisons the weights steps
before any metric moves, a bad batch spikes the loss into divergence, and
by the time a human looks, every checkpoint in the retention window is
garbage. This module is the detection half of the integrity plane
(`ResilientRunner`'s rollback-to-last-good is the recovery half):

* **bucket sentinel** (``MXNET_TPU_INTEGRITY=1``) — an all-finite check
  FUSED into the existing flat comm-bucket programs (`engine.
  fused_bucket_fn(with_finite=True)`): one extra scalar reduction riding
  the flat vector the collective already touches, so XLA folds it into the
  bucket launch — near-free on device. The kvstore bucketed push, the ZeRO
  reduce-scatter legs, and the FusedTrainStep's whole-step program all
  carry it; a false scalar raises `DivergenceError` BEFORE the poisoned
  values reach any store/updater write, naming the bucket keys.
* **loss sentinel** — a non-finite loss always trips; with
  ``MXNET_TPU_LOSS_SPIKE_FACTOR=k`` set, a loss exceeding ``k ×`` the
  rolling median of the last ``MXNET_TPU_ANOMALY_WINDOW`` steps (the same
  machinery as `telemetry.anomaly`'s step-time spike detector, same
  warm-up) trips too — the "diverged without NaN" case.

Both raise a structured `DivergenceError` carrying the offending step
(`set_step` — the runner stamps it each step), the sentinel site, the
bucket/param keys, and the flight-recorder ring tail.

Counters: ``integrity.checks`` (buckets checked), ``integrity.divergences``
(+ per-site), ``integrity.loss_spikes``, and the AMP bridge
``integrity.amp_overflow`` / ``integrity.amp_skipped_steps`` — so an AMP
overflow skip and an integrity rollback are distinguishable in telemetry.

When NOT to use: the in-program check is near-free, but the *host* pays
one scalar sync per bucket when enabled — leave it off for
max-throughput runs that already trust their data pipeline, on for any
run long enough that a silent poisoning costs more than the sync.
"""
from __future__ import annotations

import os
import threading

from .errors import DivergenceError

__all__ = ["enabled", "comm_checksum_enabled", "loss_spike_factor",
           "set_step", "current_step",
           "check_finite", "check_scalar", "observe_loss",
           "note_amp_overflow", "note_amp_skip", "reset"]

# loss-spike detection reuses the anomaly tracker's warm-up discipline: no
# verdicts until the window has seen enough losses to trust a median
_WARMUP = 8

_STATE = threading.local()
_LOCK = threading.Lock()
_LOSS_WINDOW = []  # rolling |loss| window for the spike detector


def enabled():
    """The sentinel master switch (env ``MXNET_TPU_INTEGRITY``)."""
    return os.environ.get("MXNET_TPU_INTEGRITY", "0").lower() \
        in ("1", "true", "yes", "on")


def comm_checksum_enabled():
    """``MXNET_TPU_COMM_CHECKSUM`` — the heavier dist-push lever: digest
    the packed bucket before the wire and all-finite the summed result
    after. NOT free (one host digest + one scalar sync per bucket), so it
    is a separate switch from the fused sentinel."""
    return os.environ.get("MXNET_TPU_COMM_CHECKSUM", "0").lower() \
        in ("1", "true", "yes", "on")


def loss_spike_factor():
    """``MXNET_TPU_LOSS_SPIKE_FACTOR`` as float, or None (spike detection
    off; non-finite losses still always trip)."""
    raw = os.environ.get("MXNET_TPU_LOSS_SPIKE_FACTOR")
    if not raw:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def _window_size():
    from ..telemetry import anomaly as _anomaly
    return _anomaly.default_window()


def set_step(step):
    """Stamp the current global step (the runner calls this each step) so
    a divergence raised deep in the comm stack can name it."""
    _STATE.step = int(step) if step is not None else None


def current_step():
    return getattr(_STATE, "step", None)


def _raise(site, keys, detail):
    from .. import telemetry as _telem
    from ..telemetry import flight as _flight
    _telem.inc("integrity.divergences")
    _telem.inc("integrity.divergences.%s" % site)
    step = current_step()
    _flight.note_event(
        "divergence", "site=%s step=%s%s"
        % (site, "?" if step is None else step,
           (" keys=[%s]" % ",".join(str(k) for k in keys)) if keys else ""))
    raise DivergenceError(
        "integrity sentinel tripped at %s%s: %s"
        % (site, "" if step is None else " (step %d)" % step, detail),
        step=step, site=site, keys=keys,
        flight_dump=_telem.flight_records())


def finite_scalar(raws):
    """ONE device scalar: all values across `raws` finite. Pure jnp — safe
    inside jit (the fused-step program composes it into its own outputs)."""
    import jax.numpy as jnp
    fin = jnp.asarray(True)
    for r in raws:
        fin = fin & jnp.isfinite(r).all()
    return fin


def check_finite(raws, site, keys=None):
    """Host-side guard over already-materialized device arrays: one fused
    finite reduction, ONE sync. Raises `DivergenceError` on a non-finite
    value. The ZeRO packed-bucket path uses this (its flat_g is already
    one array per bucket)."""
    from .. import telemetry as _telem
    _telem.inc("integrity.checks")
    if bool(finite_scalar(raws)):
        return
    _raise(site, keys, "non-finite value in gradient bucket")


def check_scalar(fin, site, keys=None):
    """Guard for a finite-scalar an in-program check already computed (the
    `fused_bucket_fn(with_finite=True)` output): one bool() sync, raise on
    False."""
    from .. import telemetry as _telem
    _telem.inc("integrity.checks")
    if bool(fin):
        return
    _raise(site, keys, "non-finite value in fused bucket program")


def observe_loss(loss, step=None):
    """Feed one step's scalar loss to the loss sentinel. Non-finite always
    trips; with MXNET_TPU_LOSS_SPIKE_FACTOR set, a loss past k× the rolling
    median (|loss|, post-warm-up) trips too. The spike joins the window
    only when it did NOT fire — a genuine regime change after a rollback
    must re-learn its baseline from clean steps."""
    import math
    if step is not None:
        set_step(step)
    try:
        val = float(loss)
    except (TypeError, ValueError):
        return
    if not math.isfinite(val):
        _raise("train.loss", None, "non-finite loss %r" % val)
    factor = loss_spike_factor()
    if factor is None:
        _append_loss(abs(val))
        return
    with _LOCK:
        win = list(_LOSS_WINDOW)
    if len(win) >= _WARMUP:
        med = sorted(win)[len(win) // 2]
        if med > 0 and abs(val) > factor * med:
            from .. import telemetry as _telem
            _telem.inc("integrity.loss_spikes")
            _raise("train.loss", None,
                   "loss %.6g exceeds %.3g x rolling median %.6g"
                   % (val, factor, med))
    _append_loss(abs(val))


def _append_loss(val):
    with _LOCK:
        _LOSS_WINDOW.append(val)
        limit = _window_size()
        if len(_LOSS_WINDOW) > limit:
            del _LOSS_WINDOW[:len(_LOSS_WINDOW) - limit]


def note_amp_overflow():
    """AMP's dynamic loss scaler found a non-finite grad: counted HERE so
    telemetry can tell an AMP overflow skip from an integrity rollback."""
    from .. import telemetry as _telem
    _telem.inc("integrity.amp_overflow")


def note_amp_skip():
    """AMP skipped the weight update for an overflowed step."""
    from .. import telemetry as _telem
    _telem.inc("integrity.amp_skipped_steps")


def reset():
    """Drop the loss window (tests; measurement-window boundaries)."""
    with _LOCK:
        del _LOSS_WINDOW[:]
    _STATE.step = None
