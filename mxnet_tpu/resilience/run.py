"""Resumable training driver: checkpoints, fault recovery, restart budget.

`ResilientRunner` wraps a per-step callable (a `gluon.FusedTrainStep`, a
`parallel.ShardedTrainStep`, or any ``step_fn(step_idx) -> loss``) with the
full recovery loop a preemptible fleet needs:

* **periodic snapshots** — every ``ckpt_every`` steps the runner captures
  training state (params, optimizer state and bookkeeping, RNG streams)
  through ``state_get`` and commits it atomically (write-then-rename, with
  ``keep=N`` retention, so a crash mid-save never corrupts the latest
  checkpoint);
* **fault handling** — transport faults at the step boundary are retried in
  place (they precede any state mutation); everything else retriable —
  `PreemptionError` (host going away), `StallError` (watchdog deadline),
  `RetryExhausted` bubbling up from comm layers, or a mid-step transport
  fault — triggers *restore-and-replay*: reload the latest snapshot, rewind
  the step counter, continue. Replay is deterministic, so an interrupted
  run reproduces the uninterrupted trajectory exactly;
* **restart budget** — ``max_restarts`` caps recovery attempts; the budget
  spent is reported, and exceeding it re-raises the last fault;
* **hang watchdog** — each step runs under ``watchdog.guard`` with
  ``step_deadline_s`` (default env ``MXNET_TPU_STEP_DEADLINE_S``), so a dead
  collective becomes a recoverable `StallError` instead of a silent hang;
* **mesh degradation** — an optional ``mesh_factory`` is re-polled after
  every restore; when the visible device set shrank (preempted hosts), the
  ``on_shrink`` hook rebuilds the step for the smaller mesh and training
  continues degraded instead of dying.

Telemetry: ``resilience.checkpoints`` / ``restores`` / ``mesh_shrinks``
counters and ``checkpoint`` / ``restore`` chrome-trace spans (retries and
stalls are counted by their own modules).
"""
from __future__ import annotations

import logging
import os
import pickle
import re
import shutil
import time

from . import faults, watchdog
from .errors import RetriableError, TransportError
from .retry import RetryPolicy, call_with_retry

__all__ = ["SnapshotCheckpointer", "ResilientRunner", "RunReport",
           "fused_step_state", "restore_fused_step_state"]

_LOG = logging.getLogger("mxnet_tpu.resilience")


# ---------------------------------------------------------------------------
# checkpoint backend
# ---------------------------------------------------------------------------
class SnapshotCheckpointer:
    """Atomic pickled-pytree checkpoints with ``keep=N`` retention.

    The dependency-free backend for host-resident state (the Gluon path, or
    any pytree of host arrays). Pod-scale sharded trees should go through
    `parallel.checkpoint.save_sharded` (orbax/OCDBT) instead — pass any
    object with the same ``save/restore/latest_step`` trio as
    ``checkpointer=`` to use it.

    Commit protocol: write ``step_N.ckpt.tmp`` → fsync → ``os.replace`` to
    ``step_N.ckpt`` → rewrite the ``LATEST`` marker the same way. A crash at
    any point leaves either the previous committed state or the new one,
    never a torn file.
    """

    _STEP_RE = re.compile(r"^step_(\d+)\.ckpt$")

    def __init__(self, path, keep=2):
        self.path = os.path.abspath(path)
        self.keep = None if keep in (None, 0) else max(1, int(keep))
        os.makedirs(self.path, exist_ok=True)

    def _file(self, step):
        return os.path.join(self.path, "step_%d.ckpt" % int(step))

    def save(self, step, tree):
        from ..util import atomic_write, write_latest_marker
        atomic_write(self._file(step),
                     pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL))
        write_latest_marker(self.path, step)
        self._retain()
        return self._file(step)

    def steps(self):
        out = []
        for name in os.listdir(self.path):
            m = self._STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        """Newest committed step — prefers the LATEST marker, falls back to
        a directory scan (marker lost/corrupt ≠ checkpoints lost)."""
        from ..util import read_latest_marker
        step = read_latest_marker(self.path)
        if step is not None and os.path.exists(self._file(step)):
            return step
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    "no checkpoint under %s" % self.path)
        with open(self._file(step), "rb") as f:
            return step, pickle.load(f)

    def _retain(self):
        if self.keep is None:
            return
        steps = self.steps()
        for step in steps[:-self.keep]:
            try:
                os.remove(self._file(step))
            except OSError:  # pragma: no cover — races with manual cleanup
                pass

    def clear(self):
        shutil.rmtree(self.path, ignore_errors=True)
        os.makedirs(self.path, exist_ok=True)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------
class RunReport:
    """What happened: per-step losses plus the recovery ledger."""

    def __init__(self):
        self.losses = []
        self.restarts = 0
        self.retries = 0
        self.steps_executed = 0     # includes replayed steps
        self.checkpoints = 0
        self.mesh_shrinks = 0

    def __repr__(self):
        return ("RunReport(steps=%d, executed=%d, restarts=%d, retries=%d, "
                "checkpoints=%d, mesh_shrinks=%d)"
                % (len(self.losses), self.steps_executed, self.restarts,
                   self.retries, self.checkpoints, self.mesh_shrinks))


class ResilientRunner:
    """Drive ``step_fn`` for N steps, surviving retriable faults.

    step_fn(step_idx) -> loss   (must be deterministic given restored state
                                 — replay correctness depends on it)
    state_get() -> pytree       (host-resident snapshot of ALL mutable
                                 training state)
    state_set(tree)             (restore that snapshot in place)
    """

    def __init__(self, step_fn, state_get, state_set, ckpt_dir=None,
                 checkpointer=None, ckpt_every=1, keep=2, max_restarts=3,
                 step_deadline_s=None, retry_policy=None, mesh_factory=None,
                 on_shrink=None, on_stall=None):
        if checkpointer is None and ckpt_dir is not None:
            checkpointer = SnapshotCheckpointer(ckpt_dir, keep=keep)
        self.step_fn = step_fn
        self.state_get = state_get
        self.state_set = state_set
        self.ckpt = checkpointer
        self.ckpt_every = max(1, int(ckpt_every))
        self.max_restarts = int(max_restarts)
        self.step_deadline_s = (step_deadline_s
                                if step_deadline_s is not None
                                else watchdog.default_deadline_s())
        self.retry_policy = retry_policy or RetryPolicy()
        self.mesh_factory = mesh_factory
        self.on_shrink = on_shrink
        self.on_stall = on_stall
        self._mesh_size = None
        if mesh_factory is not None:
            mesh = mesh_factory()
            self._mesh_size = getattr(getattr(mesh, "devices", None),
                                      "size", None)

    # ------------------------------------------------------------------
    def _save(self, step, report):
        if self.ckpt is None:
            return
        from .. import telemetry as _telem
        with _telem.span("checkpoint", "resilience"):
            self.ckpt.save(step, self.state_get())
        _telem.inc("resilience.checkpoints")
        report.checkpoints += 1

    def _restore(self, report, cause):
        if self.ckpt is None:
            raise cause
        from .. import telemetry as _telem
        with _telem.span("restore", "resilience"):
            try:
                step, tree = self.ckpt.restore()
            except FileNotFoundError:
                # nothing saved yet (e.g. start_step off the ckpt cadence):
                # the original fault is the story, not the empty dir
                raise cause from None
            self.state_set(tree)
        _telem.inc("resilience.restores")
        report.restarts += 1
        _LOG.warning("resilience: restored step %d after %s: %s",
                     step, type(cause).__name__, cause)
        self._maybe_shrink(report)
        return step

    def _maybe_shrink(self, report):
        """Poll the device set; a shrink means preempted hosts — rebuild for
        the smaller mesh via on_shrink instead of dying on the next
        collective."""
        if self.mesh_factory is None:
            return
        mesh = self.mesh_factory()
        size = getattr(getattr(mesh, "devices", None), "size", None)
        if (size is not None and self._mesh_size is not None
                and size < self._mesh_size):
            from .. import telemetry as _telem
            _telem.inc("resilience.mesh_shrinks")
            report.mesh_shrinks += 1
            _LOG.warning(
                "resilience: device set shrank %d -> %d; degrading to the "
                "smaller mesh", self._mesh_size, size)
            if self.on_shrink is not None:
                new_step_fn = self.on_shrink(mesh)
                if new_step_fn is not None:
                    self.step_fn = new_step_fn
        self._mesh_size = size

    # ------------------------------------------------------------------
    def _boundary_check(self, step):
        """The pre-mutation fault boundary: injected/transient transport
        faults raised HERE are retried in place (nothing has changed yet).
        Counted faults deeper in the step go down the restore path."""
        faults.check("run.step", context="step=%d" % step)

    def _run_one(self, step, report):
        def on_retry(attempt, exc):
            report.retries += 1
        call_with_retry(self._boundary_check, step, site="run.step",
                        policy=self.retry_policy,
                        retry_on=lambda e: isinstance(e, TransportError),
                        on_retry=on_retry)
        with watchdog.guard("run.step", deadline_s=self.step_deadline_s,
                            on_stall=self.on_stall):
            loss = self.step_fn(step)
        report.steps_executed += 1
        return loss

    def run(self, num_steps, start_step=0, resume=False):
        """Run steps ``[start_step, num_steps)``; returns a `RunReport`.

        resume=True restores the newest checkpoint first (auto-resume after
        a process-level kill: relaunch with the same ckpt_dir and resume).
        """
        report = RunReport()
        report.losses = [None] * num_steps
        step = start_step
        if resume and self.ckpt is not None \
                and self.ckpt.latest_step() is not None:
            step = self._restore(report, RetriableError("process resume"))
            report.restarts -= 1  # a requested resume is not a failure
        last_saved = None
        while step < num_steps:
            if (self.ckpt is not None and step % self.ckpt_every == 0
                    and last_saved != step):
                self._save(step, report)
                last_saved = step
            try:
                loss = self._run_one(step, report)
            except RetriableError as exc:
                if report.restarts >= self.max_restarts:
                    _LOG.error(
                        "resilience: restart budget (%d) exhausted",
                        self.max_restarts)
                    raise
                step = self._restore(report, exc)
                last_saved = step  # that snapshot is already on disk
                continue
            report.losses[step] = self._to_float(loss)
            step += 1
        return report

    @staticmethod
    def _to_float(loss):
        try:
            return float(loss.asnumpy()) if hasattr(loss, "asnumpy") \
                else float(loss)
        except (TypeError, ValueError):
            return loss

    # ------------------------------------------------------------------
    # adapters
    # ------------------------------------------------------------------
    @classmethod
    def for_fused_step(cls, fused, batch_fn, **kwargs):
        """Wrap a `gluon.FusedTrainStep`: state capture/restore covers the
        net's params (train + aux), optimizer state and host bookkeeping
        (num_update / per-index counts / schedules), and the mx.random key
        table — kill-and-resume replays the uninterrupted trajectory
        exactly. ``batch_fn(step_idx) -> (data, label)`` must be
        deterministic per index (re-fetchable for replay)."""
        data, label = batch_fn(0)
        if not fused._built:
            from ..gluon.fused_step import _flatten
            flat, _ = _flatten(data, "input")
            fused._build(flat[0].context, data, label)

        def step_fn(i):
            d, l = batch_fn(i)
            return fused(d, l)

        return cls(step_fn,
                   state_get=lambda: fused_step_state(fused),
                   state_set=lambda tree: restore_fused_step_state(
                       fused, tree),
                   **kwargs)

    @classmethod
    def for_sharded_step(cls, step, params, opt_state, batch_fn, **kwargs):
        """Wrap a `parallel.ShardedTrainStep` (functional path): the runner
        owns the (params, opt_state) pytrees; read the final values off the
        returned runner via ``runner.holder``."""
        import jax
        import numpy as _np
        holder = {"params": params, "opt_state": opt_state}

        def step_fn(i):
            p, o, loss = step(holder["params"], holder["opt_state"],
                              batch_fn(i), i)
            holder["params"], holder["opt_state"] = p, o
            return loss

        def state_get():
            return jax.tree_util.tree_map(
                lambda x: _np.asarray(x),
                {"params": holder["params"],
                 "opt_state": holder["opt_state"]})

        def state_set(tree):
            import jax.numpy as jnp
            holder["params"] = jax.tree_util.tree_map(
                jnp.asarray, tree["params"])
            holder["opt_state"] = jax.tree_util.tree_map(
                jnp.asarray, tree["opt_state"])

        runner = cls(step_fn, state_get, state_set, **kwargs)
        runner.holder = holder
        return runner


# ---------------------------------------------------------------------------
# FusedTrainStep state capture (module-level so tooling can reuse it)
# ---------------------------------------------------------------------------
def _rng_capture():
    import jax
    import numpy as _np
    from .. import random as _random
    table = _random._table()
    return {k: _np.asarray(jax.random.key_data(v))
            for k, v in table.items()}


def _rng_restore(snap):
    import jax
    from .. import random as _random
    table = _random._table()
    table.clear()
    for k, data in snap.items():
        table[k] = jax.random.wrap_key_data(data)


def fused_step_state(fused):
    """Host-resident snapshot of everything a FusedTrainStep mutates."""
    import numpy as _np
    from ..gluon.fused_step import _state_raws
    if not fused._built:
        raise RuntimeError(
            "fused_step_state: step not built yet — run one step or use "
            "ResilientRunner.for_fused_step (it pre-builds)")

    def host(x):
        if x is None:
            return None
        if isinstance(x, (tuple, list)):
            return tuple(host(e) for e in x)
        return _np.asarray(x)

    opt = fused._trainer._optimizer
    return {
        "train": [host(p._read()) for p in fused._train_nds],
        "other": [host(p._read()) for p in fused._other_nds],
        "states": [host(_state_raws(s)) for s in fused._states],
        "optimizer": _opt_capture(opt),
        "rng": _rng_capture(),
        "wall_time": time.time(),
    }


# mutable host-side schedule attrs some optimizers carry (Nadam's running
# m_schedule product, LBSGD's lbmult) — the param-facing state lives in
# `states` already
_OPT_SCALAR_ATTRS = ("m_schedule", "lbmult")


def _opt_capture(opt):
    """Host bookkeeping only — NOT a pickle of the optimizer (param_dict
    holds live Parameters; the arrays are captured separately)."""
    return {
        "num_update": opt.num_update,
        "index_update_count": dict(opt._index_update_count),
        "attrs": {a: getattr(opt, a) for a in _OPT_SCALAR_ATTRS
                  if hasattr(opt, a)},
        "sched": (pickle.dumps(opt.lr_scheduler,
                               protocol=pickle.HIGHEST_PROTOCOL)
                  if opt.lr_scheduler is not None else None),
    }


def _opt_restore(opt, snap):
    opt.num_update = snap["num_update"]
    # in place: _all_index_update_counts[0] aliases this dict
    opt._index_update_count.clear()
    opt._index_update_count.update(snap["index_update_count"])
    for a, v in snap["attrs"].items():
        setattr(opt, a, v)
    if snap["sched"] is not None and opt.lr_scheduler is not None:
        clone = pickle.loads(snap["sched"])
        opt.lr_scheduler.__dict__.update(clone.__dict__)


def restore_fused_step_state(fused, tree):
    """Inverse of `fused_step_state` — writes the snapshot back in place
    (the jitted programs keep their captured NDArray objects)."""
    import jax.numpy as jnp
    from ..gluon.fused_step import _state_write

    def dev(x):
        return None if x is None else jnp.asarray(x)

    for p, raw in zip(fused._train_nds, tree["train"]):
        p._write(dev(raw))
    for p, raw in zip(fused._other_nds, tree["other"]):
        p._write(dev(raw))

    def dev_tree(x):
        if x is None:
            return None
        if isinstance(x, tuple):
            return tuple(dev_tree(e) for e in x)
        return dev(x)

    for s, raws in zip(fused._states, tree["states"]):
        _state_write(s, dev_tree(raws))
    _opt_restore(fused._trainer._optimizer, tree["optimizer"])
    # the host scalar cache (lr/t schedules) is stale for the rewound counts
    fused._scal_cache = None
    _rng_restore(tree["rng"])
