"""Resumable training driver: checkpoints, fault recovery, restart budget.

`ResilientRunner` wraps a per-step callable (a `gluon.FusedTrainStep`, a
`parallel.ShardedTrainStep`, or any ``step_fn(step_idx) -> loss``) with the
full recovery loop a preemptible fleet needs:

* **periodic snapshots** — every ``ckpt_every`` steps the runner captures
  training state (params, optimizer state and bookkeeping, RNG streams)
  through ``state_get`` and commits it atomically (write-then-rename, with
  ``keep=N`` retention, so a crash mid-save never corrupts the latest
  checkpoint);
* **fault handling** — transport faults at the step boundary are retried in
  place (they precede any state mutation); everything else retriable —
  `PreemptionError` (host going away), `StallError` (watchdog deadline),
  `RetryExhausted` bubbling up from comm layers, or a mid-step transport
  fault — triggers *restore-and-replay*: reload the latest snapshot, rewind
  the step counter, continue. Replay is deterministic, so an interrupted
  run reproduces the uninterrupted trajectory exactly;
* **restart budget** — ``max_restarts`` caps recovery attempts; the budget
  spent is reported, and exceeding it re-raises the last fault;
* **hang watchdog** — each step runs under ``watchdog.guard`` with
  ``step_deadline_s`` (default env ``MXNET_TPU_STEP_DEADLINE_S``), so a dead
  collective becomes a recoverable `StallError` instead of a silent hang;
* **elastic re-layout** — an optional ``mesh_factory`` is re-polled after
  every restore (and, for grow-back, at every checkpoint boundary); when
  the visible device set changed, the runner re-lays the restored (or
  live) training state onto the new mesh and rebuilds the step
  automatically — the ``for_sharded_step`` / ``for_fused_step`` adapters
  wire the re-layout; an ``on_shrink`` / ``on_grow`` hook overrides it;
* **coordinated commit** — with ``commit=`` (a `commit.CommitCoordinator`,
  or True), checkpoints run the two-phase protocol: payload first, then a
  fleet-wide min-step election over the jax.distributed coordinator, and
  only then the LATEST marker — every rank of a pod restores the same
  *elected* step even when one rank crashed mid-commit a step ahead;
* **proactive preemption** — with ``preempt_listener=`` (a
  `preempt.PreemptionListener`, or True), SIGTERM / maintenance-event
  notices trigger an *immediate* coordinated checkpoint at the next step
  boundary, so resume replays zero steps instead of a whole
  ``ckpt_every`` window.

Telemetry: ``resilience.checkpoints`` / ``restores`` / ``mesh_shrinks`` /
``mesh_grows`` / ``proactive_checkpoints`` counters and ``checkpoint`` /
``restore`` chrome-trace spans (retries, stalls, elections, and notices
are counted by their own modules).
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import re
import shutil
import time

from . import faults, watchdog
from .errors import (CheckpointCorruptError, DivergenceError,
                     FatalTrainingError, ResilienceError, RetriableError,
                     TransportError)
from .retry import RetryPolicy, call_with_retry

__all__ = ["SnapshotCheckpointer", "ResilientRunner", "RunReport",
           "fused_step_state", "restore_fused_step_state"]

_LOG = logging.getLogger("mxnet_tpu.resilience")


# ---------------------------------------------------------------------------
# checkpoint backend
# ---------------------------------------------------------------------------
class SnapshotCheckpointer:
    """Atomic pickled-pytree checkpoints with ``keep=N`` retention.

    The dependency-free backend for host-resident state (the Gluon path, or
    any pytree of host arrays). Pod-scale sharded trees should go through
    `parallel.checkpoint.save_sharded` (orbax/OCDBT) instead — pass any
    object with the same ``save/restore/latest_step`` trio as
    ``checkpointer=`` to use it.

    Commit protocol: write ``step_N.ckpt.tmp`` → fsync → ``os.replace`` to
    ``step_N.ckpt`` → rewrite the ``LATEST`` marker the same way. A crash at
    any point leaves either the previous committed state or the new one,
    never a torn file.

    The two phases are also exposed separately for pod-coordinated runs:
    ``prepare(step, tree)`` makes the payload durable WITHOUT moving the
    marker, ``commit(step)`` flips the marker — the runner's
    `commit.CommitCoordinator` election sits between them, so the marker
    only ever names a step the whole fleet has. Both phases carry fault
    sites: ``checkpoint.save`` fires after the payload is durable and
    before the marker moves (the crashed-mid-commit shape), and
    ``checkpoint.restore`` fires on the way into a restore.

    Integrity (ISSUE 20): every payload is stamped with a sha256 sidecar
    (``step_N.ckpt.sha256``) at prepare time and verified on restore. A
    mismatched / truncated / unpicklable payload is counted
    (``checkpoint.corrupt``) and the restore FALLS BACK to the next-oldest
    durable snapshot instead of crashing; only a retention window with no
    good snapshot at all raises `CheckpointCorruptError`. The
    ``checkpoint.corrupt`` fault site (a `faults.transform` site) sits
    between pickling and the atomic write, so a ``corrupt`` plan entry
    flips bytes ON DISK while the sidecar keeps the true digest — the
    injectable torn-disk drill.
    """

    _STEP_RE = re.compile(r"^step_(\d+)\.ckpt$")

    def __init__(self, path, keep=2):
        self.path = os.path.abspath(path)
        self.keep = None if keep in (None, 0) else max(1, int(keep))
        os.makedirs(self.path, exist_ok=True)

    def _file(self, step):
        return os.path.join(self.path, "step_%d.ckpt" % int(step))

    def _digest_file(self, step):
        return self._file(step) + ".sha256"

    def prepare(self, step, tree):
        """Phase 1: make the step's payload durable. The LATEST marker does
        not move — an uncommitted payload is invisible to `latest_step`
        (marker precedence) and to any fleet that elects over committed
        steps. Ends at the ``checkpoint.save`` fault site: an injected
        crash here IS the mid-commit crash."""
        from ..util import atomic_write
        blob = pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)
        # digest BEFORE the corrupt transform: the sidecar must hold the
        # truth so injected on-disk corruption is detectable, exactly like
        # a real torn write under a checksum stamped at save time
        digest = hashlib.sha256(blob).hexdigest()
        blob = faults.transform("checkpoint.corrupt", blob,
                                context="step=%d payload" % step)
        atomic_write(self._file(step), blob)
        atomic_write(self._digest_file(step), digest.encode())
        faults.check("checkpoint.save", context="step=%d mid-commit" % step)
        return self._file(step)

    def commit(self, step):
        """Phase 2: flip LATEST to `step` and apply retention. Refuses
        (False) when `step`'s payload is not durable here — an elected
        step that predates this rank's retention window must not produce
        a marker pointing at nothing."""
        from ..util import write_latest_marker
        if not os.path.exists(self._file(step)):
            _LOG.warning(
                "checkpoint: not committing step %s — payload missing "
                "under %s (marker unchanged)", step, self.path)
            return False
        write_latest_marker(self.path, step)
        self._retain()
        return True

    def save(self, step, tree):
        self.prepare(step, tree)
        self.commit(step)
        return self._file(step)

    def prepared_steps(self):
        """Every durable payload, committed or not (directory scan)."""
        return self.steps()

    def steps(self):
        out = []
        for name in os.listdir(self.path):
            m = self._STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        """Newest committed step — prefers the LATEST marker, falls back to
        a directory scan (marker lost/corrupt ≠ checkpoints lost)."""
        from ..util import read_latest_marker
        step = read_latest_marker(self.path)
        if step is not None and os.path.exists(self._file(step)):
            return step
        steps = self.steps()
        return steps[-1] if steps else None

    def _load_verified(self, step):
        """Read + verify + unpickle ONE step's payload. Raises ValueError
        on a checksum mismatch / truncation / unpickle failure — the
        caller's fallback walk treats all three identically (the disk
        lied; the sidecar is the truth)."""
        with open(self._file(step), "rb") as f:
            blob = f.read()
        digest_path = self._digest_file(step)
        if os.path.exists(digest_path):
            with open(digest_path, "rb") as f:
                want = f.read().decode().strip()
            got = hashlib.sha256(blob).hexdigest()
            if got != want:
                raise ValueError(
                    "checksum mismatch for step %d: sha256 %s != stamped %s"
                    % (step, got[:12], want[:12]))
        # pre-checksum checkpoints (no sidecar) still get the unpickle
        # sanity net below — never a crash on a truncated payload
        try:
            return pickle.loads(blob)
        except Exception as exc:
            raise ValueError(
                "unpicklable payload for step %d: %s: %s"
                % (step, type(exc).__name__, exc)) from exc

    def restore(self, step=None):
        """Restore `step` (default: newest committed). A corrupt payload —
        checksum mismatch, truncation, unpickle failure — is counted
        (``checkpoint.corrupt``) and the restore falls back to the
        next-oldest durable snapshot; `CheckpointCorruptError` only when
        every candidate is bad."""
        from .. import telemetry as _telem
        from ..telemetry import flight as _flight
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    "no checkpoint under %s" % self.path)
        faults.check("checkpoint.restore", context="step=%d" % step)
        candidates = [step] + [s for s in reversed(self.steps()) if s < step]
        tried = []
        for cand in candidates:
            if not os.path.exists(self._file(cand)):
                continue
            try:
                tree = self._load_verified(cand)
            except ValueError as exc:
                tried.append(cand)
                _telem.inc("checkpoint.corrupt")
                _flight.note_event("checkpoint_corrupt",
                                   "step=%d: %s" % (cand, exc))
                _LOG.warning(
                    "checkpoint: step %d payload is corrupt (%s) — falling "
                    "back to the next-oldest snapshot", cand, exc)
                continue
            if tried:
                _telem.inc("checkpoint.corrupt_fallbacks")
            return cand, tree
        raise CheckpointCorruptError(
            "every snapshot under %s failed verification (steps tried: %s)"
            % (self.path, tried or "none durable"), steps_tried=tried)

    def _retain(self):
        if self.keep is None:
            return
        steps = self.steps()
        for step in steps[:-self.keep]:
            try:
                os.remove(self._file(step))
                if os.path.exists(self._digest_file(step)):
                    os.remove(self._digest_file(step))
            except OSError:  # pragma: no cover — races with manual cleanup
                pass

    def clear(self):
        shutil.rmtree(self.path, ignore_errors=True)
        os.makedirs(self.path, exist_ok=True)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------
class RunReport:
    """What happened: per-step losses plus the recovery ledger."""

    def __init__(self):
        self.losses = []
        self.restarts = 0
        self.retries = 0
        self.steps_executed = 0     # includes replayed steps
        self.replayed_steps = 0     # re-executed after a restore rewind
        self.checkpoints = 0
        self.proactive_ckpts = 0    # checkpoints forced by a preempt notice
        self.mesh_shrinks = 0
        self.mesh_grows = 0
        self.rollbacks = 0          # divergence rollbacks-to-last-good
        self.skipped_batches = 0    # batches skipped past poisoned windows
        self.recovery_time_s = 0.0  # wall time spent inside restores

    def __repr__(self):
        return ("RunReport(steps=%d, executed=%d, replayed=%d, restarts=%d, "
                "retries=%d, checkpoints=%d, proactive=%d, mesh_shrinks=%d, "
                "mesh_grows=%d, rollbacks=%d, skipped_batches=%d, "
                "recovery_time_s=%.3f)"
                % (len(self.losses), self.steps_executed,
                   self.replayed_steps, self.restarts, self.retries,
                   self.checkpoints, self.proactive_ckpts, self.mesh_shrinks,
                   self.mesh_grows, self.rollbacks, self.skipped_batches,
                   self.recovery_time_s))


class ResilientRunner:
    """Drive ``step_fn`` for N steps, surviving retriable faults.

    step_fn(step_idx) -> loss   (must be deterministic given restored state
                                 — replay correctness depends on it)
    state_get() -> pytree       (host-resident snapshot of ALL mutable
                                 training state)
    state_set(tree)             (restore that snapshot in place)

    relayout(mesh) -> step_fn   (optional: re-lay the CURRENT training
                                 state onto `mesh` and return the rebuilt
                                 step — the elastic path; the
                                 `for_sharded_step` / `for_fused_step`
                                 adapters provide it automatically, so a
                                 mesh shrink/grow-back needs no user code.
                                 `on_shrink` / `on_grow` override it.)
    commit                      (True or a `commit.CommitCoordinator`:
                                 two-phase fleet-agreed checkpoints)
    preempt_listener            (True or a `preempt.PreemptionListener`:
                                 proactive checkpoint on SIGTERM /
                                 maintenance notices)
    skip_policy(step, exc)->int (rollback mode: how many batches to skip
                                 past a `DivergenceError` at `step`;
                                 default skip-one)
    rollback_budget             (max CONSECUTIVE rollbacks — no completed
                                 step between them — before escalating to
                                 `FatalTrainingError`; default env
                                 ``MXNET_TPU_ROLLBACK_BUDGET`` or 3)

    Rollback-to-last-good (ISSUE 20): a `DivergenceError` (the integrity
    sentinel — non-finite bucket/fused-step values, loss spike) is handled
    as its OWN recovery mode, distinct from restore-and-replay: the runner
    restores the last *committed* snapshot, then advances the data stream
    past the poisoned batch window, so the replayed trajectory never
    re-feeds the batch that diverged. Skip windows are bit-deterministic
    (pure step-index arithmetic, RNG/step state rides the snapshot) and
    travel inside dict checkpoints, so a process-level resume preserves
    them. ``step_fn`` receives the skip-adjusted DATA index.
    """

    def __init__(self, step_fn, state_get, state_set, ckpt_dir=None,
                 checkpointer=None, ckpt_every=1, keep=2, max_restarts=3,
                 step_deadline_s=None, retry_policy=None, mesh_factory=None,
                 on_shrink=None, on_grow=None, relayout=None, on_stall=None,
                 commit=None, preempt_listener=None, skip_policy=None,
                 rollback_budget=None):
        if checkpointer is None and ckpt_dir is not None:
            checkpointer = SnapshotCheckpointer(ckpt_dir, keep=keep)
        self.step_fn = step_fn
        self.state_get = state_get
        self.state_set = state_set
        self.ckpt = checkpointer
        self.ckpt_every = max(1, int(ckpt_every))
        self.max_restarts = int(max_restarts)
        self.step_deadline_s = (step_deadline_s
                                if step_deadline_s is not None
                                else watchdog.default_deadline_s())
        self.retry_policy = retry_policy or RetryPolicy()
        self.mesh_factory = mesh_factory
        self.on_shrink = on_shrink
        self.on_grow = on_grow
        self.relayout = relayout
        self.on_stall = on_stall
        if commit is True:
            from .commit import CommitCoordinator
            commit = CommitCoordinator()
        self.commit = commit or None
        self._own_listener = preempt_listener is True
        if preempt_listener is True:
            from .preempt import PreemptionListener
            preempt_listener = PreemptionListener()
        self.preempt_listener = preempt_listener or None
        self.skip_policy = skip_policy or (lambda step, exc: 1)
        if rollback_budget is None:
            try:
                rollback_budget = int(os.environ.get(
                    "MXNET_TPU_ROLLBACK_BUDGET", "3"))
            except (TypeError, ValueError):
                rollback_budget = 3
        self.rollback_budget = max(1, int(rollback_budget))
        # {from_step: batches_to_skip} — the poisoned-window ledger; the
        # effective data index for step s is s + sum(counts at steps <= s)
        self._skip_windows = {}
        self._consecutive_rollbacks = 0
        # last few save durations (rolling, this runner's own saves) —
        # the SIGTERM budgeter's evidence
        from collections import deque
        self._save_ms_window = deque(maxlen=8)
        self._mesh_size = None
        if mesh_factory is not None:
            mesh = mesh_factory()
            self._mesh_size = getattr(getattr(mesh, "devices", None),
                                      "size", None)

    # ------------------------------------------------------------------
    def _save(self, step, report, proactive=False):
        if self.ckpt is None:
            return
        from .. import telemetry as _telem
        from ..telemetry import flight as _flight
        t0 = time.monotonic()
        with _telem.span("checkpoint", "resilience"):
            tree = self.state_get()
            if isinstance(tree, dict) and "comm_schedule" not in tree:
                # autotuned comm schedule rides the checkpoint so a
                # relaunch skips the warm-up sweep (ISSUE 19)
                from .. import engine as _engine
                sched = _engine.schedule_payload()
                if sched is not None:
                    tree = dict(tree)
                    tree["comm_schedule"] = sched
            if isinstance(tree, dict) and self._skip_windows \
                    and "integrity_skip" not in tree:
                # poisoned-batch skip windows ride the checkpoint so a
                # process-level resume keeps skipping the same batches
                tree = dict(tree)
                tree["integrity_skip"] = dict(self._skip_windows)
            if self.commit is not None:
                # two-phase: payload durable everywhere BEFORE any marker
                # moves; the marker then names the fleet-elected step
                self.ckpt.prepare(step, tree)
                elected = self.commit.elect(step, kind="save")
                self.ckpt.commit(step if elected is None else elected)
            else:
                self.ckpt.save(step, tree)
        # the save-cost ledger the SIGTERM budgeter reads: skipping a
        # proactive save is only safe when we KNOW how slow saves run
        save_ms = (time.monotonic() - t0) * 1e3
        _telem.observe("ckpt.save_ms", save_ms)
        self._save_ms_window.append(save_ms)
        _telem.inc("resilience.checkpoints")
        _flight.note_event("proactive_ckpt" if proactive else "checkpoint",
                           "step=%d" % step)
        report.checkpoints += 1
        if proactive:
            _telem.inc("resilience.proactive_checkpoints")
            report.proactive_ckpts += 1

    def _worst_save_ms(self):
        """Max save time over the last few saves THIS runner made, or —
        before its first save — the process-local ckpt.save_ms histogram's
        max as a coarse prior (a previous runner in this process, or a
        caller-seeded estimate; the registry does not survive a process
        relaunch). None with no history at all. A rolling window, not the
        lifetime max: one cold-compile outlier save must not disable
        proactive checkpoints for the rest of a long run once saves are
        fast again."""
        if self._save_ms_window:
            return max(self._save_ms_window)
        from .. import telemetry as _telem
        hist = _telem.registry.get("ckpt.save_ms")
        if hist is None:
            return None
        return hist.snapshot().get("max")

    def _restore(self, report, cause):
        if self.ckpt is None:
            raise cause
        from .. import telemetry as _telem
        t0 = time.monotonic()
        with _telem.span("restore", "resilience"):
            step = self.ckpt.latest_step()
            if self.commit is not None:
                # restore election: every rank rewinds to the step the
                # FLEET committed, not to its own (possibly ahead) marker
                step = self.commit.elect(step, kind="restore")
            if step is None:
                # nothing saved yet (e.g. start_step off the ckpt cadence):
                # the original fault is the story, not the empty dir
                raise cause from None
            try:
                step, tree = self.ckpt.restore(step)
            except FileNotFoundError:
                raise cause from None
            if isinstance(tree, dict) and tree.get("comm_schedule") \
                    is not None:
                from .. import engine as _engine
                _engine.restore_schedule(tree.pop("comm_schedule"))
            if isinstance(tree, dict) and "integrity_skip" in tree:
                # merge by max: in-process windows added AFTER this
                # snapshot was taken must survive the restore (else a
                # second rollback would replay the same poisoned batch)
                for f, c in (tree.pop("integrity_skip") or {}).items():
                    f = int(f)
                    self._skip_windows[f] = max(
                        self._skip_windows.get(f, 0), int(c))
            self.state_set(tree)
        _telem.inc("resilience.restores")
        from ..telemetry import flight as _flight
        _flight.note_event("restore", "step=%d cause=%s"
                           % (step, type(cause).__name__))
        report.restarts += 1
        report.recovery_time_s += time.monotonic() - t0
        _LOG.warning("resilience: restored step %d after %s: %s",
                     step, type(cause).__name__, cause)
        self._maybe_relayout(report)
        return step

    def _maybe_relayout(self, report, grow_only=False):
        """Poll the device set; on a change, re-lay the current training
        state onto the new mesh. A shrink means preempted hosts (rebuild
        smaller instead of dying on the next collective); a grow means
        capacity returned (rebuild bigger instead of running degraded
        forever). The `relayout` adapter does the re-laying; `on_shrink` /
        `on_grow` hooks override it. With grow_only=True (the periodic
        checkpoint-boundary poll over LIVE state) a shrink is left for the
        fault path — live arrays on vanished devices must go through
        restore, not relayout."""
        if self.mesh_factory is None:
            return
        mesh = self.mesh_factory()
        size = getattr(getattr(mesh, "devices", None), "size", None)
        if size is None or self._mesh_size is None or size == self._mesh_size:
            if not grow_only:
                self._mesh_size = size
            return
        from .. import telemetry as _telem
        if size < self._mesh_size:
            if grow_only:
                return
            _telem.inc("resilience.mesh_shrinks")
            report.mesh_shrinks += 1
            hook = self.on_shrink
            _LOG.warning(
                "resilience: device set shrank %d -> %d; degrading to the "
                "smaller mesh", self._mesh_size, size)
        else:
            _telem.inc("resilience.mesh_grows")
            report.mesh_grows += 1
            hook = self.on_grow
            _LOG.warning(
                "resilience: device set grew %d -> %d; re-laying state "
                "back onto the larger mesh", self._mesh_size, size)
        if hook is None:
            hook = self.relayout
        if hook is not None:
            new_step_fn = hook(mesh)
            if new_step_fn is not None:
                self.step_fn = new_step_fn
        self._mesh_size = size

    # margin over the rolling max save time when deciding whether a
    # proactive save still fits the announced grace window
    _SAVE_BUDGET_MARGIN = 1.5

    def _check_preempt(self, step, report):
        """Step-boundary preemption check: a pending notice triggers an
        immediate (coordinated, off-cadence) checkpoint, then surfaces as
        the `PreemptionError` the recovery path already understands —
        resume replays zero steps instead of a ckpt_every window.

        Deadline awareness: the notice carries the announced grace window
        (~30 s SIGTERM contract). When the remaining window cannot fit the
        rolling max save time (`ckpt.save_ms` × margin), the save is
        SKIPPED — a checkpoint the host dies in the middle of is worse
        than replaying from the last good one — and recovery falls back to
        restore-and-replay."""
        listener = self.preempt_listener
        if listener is None:
            return
        notice = listener.pending()
        if notice is None:
            return
        from .. import telemetry as _telem
        from ..telemetry import flight as _flight
        from .errors import PreemptionError
        _flight.note_event("preempt_notice",
                           "%s: %s" % (notice.source, notice.reason))
        saved = False
        if self.ckpt is not None:
            remaining_s = notice.remaining_s()
            worst_ms = self._worst_save_ms()
            # no budget at all (stale/late notice, or a long step ate the
            # window) means skip even with no save history — starting a
            # save with zero budget GUARANTEES the torn-write outcome
            over_budget = remaining_s <= 0 or (
                worst_ms is not None and
                worst_ms * self._SAVE_BUDGET_MARGIN / 1e3 > remaining_s)
            if over_budget:
                _telem.inc("resilience.preempt.save_skipped")
                _flight.note_event("preempt_save_skipped",
                                   "worst=%sms remaining=%.1fs"
                                   % ("%.0f" % worst_ms
                                      if worst_ms is not None else "?",
                                      remaining_s))
                _LOG.warning(
                    "preempt: SKIPPING the proactive save — worst save "
                    "%s ms (×%.1f margin) does not fit the %.1f s left "
                    "in the grace window; will restore-and-replay instead",
                    "%.0f" % worst_ms if worst_ms is not None else "?",
                    self._SAVE_BUDGET_MARGIN, remaining_s)
            else:
                self._save(step, report, proactive=True)
                saved = True
        listener.clear()
        raise PreemptionError(
            "preemption notice (%s): %s%s"
            % (notice.source, notice.reason,
               " — proactive checkpoint committed at step %d" % step
               if saved
               else (" (proactive save skipped — grace window too short)"
                     if self.ckpt is not None
                     else " (no checkpointer configured — nothing saved)")))

    # ------------------------------------------------------------------
    def _boundary_check(self, step):
        """The pre-mutation fault boundary: injected/transient transport
        faults raised HERE are retried in place (nothing has changed yet).
        Counted faults deeper in the step go down the restore path."""
        faults.check("run.step", context="step=%d" % step)

    def data_index(self, step):
        """The data-stream index `step` consumes: the step index advanced
        past every skip window at or before it. Pure arithmetic over the
        window ledger — bit-deterministic across replay and resume."""
        if not self._skip_windows:
            return step
        return step + sum(c for f, c in self._skip_windows.items()
                          if f <= step)

    def _run_one(self, step, report):
        def on_retry(attempt, exc):
            report.retries += 1
        call_with_retry(self._boundary_check, step, site="run.step",
                        policy=self.retry_policy,
                        retry_on=lambda e: isinstance(e, TransportError),
                        on_retry=on_retry)
        from . import integrity as _integrity
        _integrity.set_step(step)
        with watchdog.guard("run.step", deadline_s=self.step_deadline_s,
                            on_stall=self.on_stall):
            loss = self.step_fn(self.data_index(step))
        report.steps_executed += 1
        if _integrity.enabled():
            # loss sentinel: non-finite always trips; a rolling-median
            # spike trips when MXNET_TPU_LOSS_SPIKE_FACTOR is set
            _integrity.observe_loss(self._to_float(loss), step)
        return loss

    def _rollback(self, step, exc, report):
        """Divergence recovery: restore the last COMMITTED snapshot, open
        a skip window over the poisoned batch(es), and continue — never an
        in-place retry (the same batch diverges again). A consecutive-
        rollback budget (reset by any completed step) escalates to fatal:
        if skipping batches does not stop the divergence, the problem is
        the run, not the data. Returns the restored step."""
        from .. import telemetry as _telem
        from ..telemetry import flight as _flight
        if self.ckpt is None:
            raise exc
        self._consecutive_rollbacks += 1
        if self._consecutive_rollbacks > self.rollback_budget:
            raise FatalTrainingError(
                "integrity: %d consecutive rollbacks exhausted the budget "
                "(%d) — divergence persists across skipped batches; last "
                "cause: %s" % (self._consecutive_rollbacks,
                               self.rollback_budget, exc)) from exc
        skip_n = max(1, int(self.skip_policy(step, exc)))
        restored = self._restore(report, exc)
        # a rollback is not a restart: it has its own ledger and budget
        report.restarts -= 1
        self._skip_windows[step] = self._skip_windows.get(step, 0) + skip_n
        report.rollbacks += 1
        report.skipped_batches += skip_n
        _telem.inc("resilience.rollbacks")
        _telem.inc("resilience.skipped_batches", skip_n)
        _flight.note_event(
            "rollback", "diverged_step=%d restored=%d skip=%d site=%s"
            % (step, restored, skip_n, getattr(exc, "site", "?")))
        _LOG.warning(
            "integrity: rolled back to step %d after divergence at step %d "
            "(%s); skipping %d batch(es)", restored, step, exc, skip_n)
        return restored

    def run(self, num_steps, start_step=0, resume=False):
        """Run steps ``[start_step, num_steps)``; returns a `RunReport`.

        resume=True restores the newest checkpoint first (auto-resume after
        a process-level kill: relaunch with the same ckpt_dir and resume).
        """
        report = RunReport()
        report.losses = [None] * num_steps
        step = start_step
        if self.preempt_listener is not None:
            self.preempt_listener.start()
        try:
            if resume and self.ckpt is not None \
                    and self.ckpt.latest_step() is not None:
                step = self._restore(report,
                                     RetriableError("process resume"))
                report.restarts -= 1  # a requested resume is not a failure
            frontier = step  # first never-executed step (replay detection)
            last_saved = None
            while step < num_steps:
                if (self.ckpt is not None and step % self.ckpt_every == 0
                        and last_saved != step):
                    self._save(step, report)
                    last_saved = step
                    # grow-back poll: capacity may have returned; re-lay
                    # the live state onto the larger mesh at a safe
                    # (just-checkpointed) boundary
                    self._maybe_relayout(report, grow_only=True)
                try:
                    self._check_preempt(step, report)
                    loss = self._run_one(step, report)
                except DivergenceError as exc:
                    # rollback-to-last-good, NOT restore-and-replay: the
                    # poisoned batch is skipped so the replayed trajectory
                    # never re-feeds it
                    step = self._rollback(step, exc, report)
                    last_saved = step  # that snapshot is already on disk
                    continue
                except RetriableError as exc:
                    if report.restarts >= self.max_restarts:
                        _LOG.error(
                            "resilience: restart budget (%d) exhausted",
                            self.max_restarts)
                        raise
                    step = self._restore(report, exc)
                    last_saved = step  # that snapshot is already on disk
                    continue
                self._consecutive_rollbacks = 0  # a completed step resets
                if step < frontier:
                    report.replayed_steps += 1
                else:
                    frontier = step + 1
                report.losses[step] = self._to_float(loss)
                step += 1
        except ResilienceError as exc:
            # the run is dying on a fault recovery could not absorb
            # (restart budget spent, fatal classification, mid-commit
            # wreckage): drop the flight recorder's step ledger to disk
            # BEFORE the exception unwinds the evidence
            from ..telemetry import flight as _flight
            path = _flight.dump_on_crash(
                "%s: %s" % (type(exc).__name__, exc),
                dir_hint=getattr(self.ckpt, "path", None))
            if path:
                _LOG.error("resilience: flight recorder dumped to %s", path)
            raise
        finally:
            if self._own_listener and self.preempt_listener is not None:
                self.preempt_listener.stop()
        return report

    @staticmethod
    def _to_float(loss):
        try:
            return float(loss.asnumpy()) if hasattr(loss, "asnumpy") \
                else float(loss)
        except (TypeError, ValueError):
            return loss

    # ------------------------------------------------------------------
    # adapters
    # ------------------------------------------------------------------
    @classmethod
    def for_fused_step(cls, fused, batch_fn, **kwargs):
        """Wrap a `gluon.FusedTrainStep`: state capture/restore covers the
        net's params (train + aux), optimizer state and host bookkeeping
        (num_update / per-index counts / schedules), and the mx.random key
        table — kill-and-resume replays the uninterrupted trajectory
        exactly. ``batch_fn(step_idx) -> (data, label)`` must be
        deterministic per index (re-fetchable for replay).

        Elastic: with a ``mesh_factory``, a mesh shrink/grow-back rebuilds
        the fused step for the new mesh automatically (`rebuild_for_mesh`)
        — the restored params re-place onto the surviving devices on the
        rebuilt step's first build. ``on_shrink``/``on_grow`` still
        override."""
        from ..gluon.fused_step import _flatten

        def build(f):
            if not f._built:
                data, label = batch_fn(0)
                flat, _ = _flatten(data, "input")
                f._build(flat[0].context, data, label)
            return f

        active = {"fused": build(fused)}

        def step_fn(i):
            # `train.batch` transform site: a `corrupt` plan entry poisons
            # this batch with NaN — the injectable divergence drill the
            # integrity sentinel + rollback path recovers from
            d, l = faults.transform("train.batch", batch_fn(i),
                                    context="index=%d" % i)
            return active["fused"](d, l)

        def relayout(mesh):
            # capture the full (just-restored, or live on grow-back)
            # training state off the current step, rebuild for the new
            # mesh, and write the state back — a fresh _build would
            # otherwise reinitialize the optimizer states it owns
            tree = fused_step_state(active["fused"])
            active["fused"] = build(active["fused"].rebuild_for_mesh(mesh))
            restore_fused_step_state(active["fused"], tree)
            return step_fn

        kwargs.setdefault("relayout", relayout)
        runner = cls(step_fn,
                     state_get=lambda: fused_step_state(active["fused"]),
                     state_set=lambda tree: restore_fused_step_state(
                         active["fused"], tree),
                     **kwargs)
        runner.active = active
        return runner

    @classmethod
    def for_sharded_step(cls, step, params, opt_state, batch_fn, **kwargs):
        """Wrap a `parallel.ShardedTrainStep` (functional path): the runner
        owns the (params, opt_state) pytrees; read the final values off the
        returned runner via ``runner.holder``.

        Elastic: with a ``mesh_factory``, a mesh shrink/grow-back is
        handled automatically — the step is rebuilt for the new mesh
        (`ShardedTrainStep.rebuild_for_mesh`) and the current
        params/optimizer trees are re-laid onto it (`place`: fresh
        rules-derived NamedShardings + device_put). No ``on_shrink`` user
        code required; the hook remains an override."""
        import jax
        import numpy as _np
        holder = {"params": params, "opt_state": opt_state}
        active = {"step": step}

        def step_fn(i):
            batch = faults.transform("train.batch", batch_fn(i),
                                     context="index=%d" % i)
            p, o, loss = active["step"](holder["params"],
                                        holder["opt_state"], batch, i)
            holder["params"], holder["opt_state"] = p, o
            return loss

        def state_get():
            return jax.tree_util.tree_map(
                lambda x: _np.asarray(x),
                {"params": holder["params"],
                 "opt_state": holder["opt_state"]})

        def state_set(tree):
            import jax.numpy as jnp
            holder["params"] = jax.tree_util.tree_map(
                jnp.asarray, tree["params"])
            holder["opt_state"] = jax.tree_util.tree_map(
                jnp.asarray, tree["opt_state"])

        def relayout(mesh):
            new_step = active["step"].rebuild_for_mesh(mesh)
            holder["params"], holder["opt_state"] = new_step.place(
                holder["params"], holder["opt_state"])
            active["step"] = new_step
            return step_fn

        kwargs.setdefault("relayout", relayout)
        runner = cls(step_fn, state_get, state_set, **kwargs)
        runner.holder = holder
        runner.active = active
        return runner


# ---------------------------------------------------------------------------
# FusedTrainStep state capture (module-level so tooling can reuse it)
# ---------------------------------------------------------------------------
def _rng_capture():
    import jax
    import numpy as _np
    from .. import random as _random
    table = _random._table()
    return {k: _np.asarray(jax.random.key_data(v))
            for k, v in table.items()}


def _rng_restore(snap):
    import jax
    from .. import random as _random
    table = _random._table()
    table.clear()
    for k, data in snap.items():
        table[k] = jax.random.wrap_key_data(data)


def fused_step_state(fused):
    """Host-resident snapshot of everything a FusedTrainStep mutates."""
    import numpy as _np
    from ..gluon.fused_step import _state_raws
    if not fused._built:
        raise RuntimeError(
            "fused_step_state: step not built yet — run one step or use "
            "ResilientRunner.for_fused_step (it pre-builds)")

    def host(x):
        if x is None:
            return None
        if isinstance(x, (tuple, list)):
            return tuple(host(e) for e in x)
        return _np.asarray(x)

    opt = fused._trainer._optimizer
    return {
        "train": [host(p._read()) for p in fused._train_nds],
        "other": [host(p._read()) for p in fused._other_nds],
        "states": [host(_state_raws(s)) for s in fused._states],
        "optimizer": _opt_capture(opt),
        "rng": _rng_capture(),
        "wall_time": time.time(),
    }


# mutable host-side schedule attrs some optimizers carry (Nadam's running
# m_schedule product, LBSGD's lbmult) — the param-facing state lives in
# `states` already
_OPT_SCALAR_ATTRS = ("m_schedule", "lbmult")


def _opt_capture(opt):
    """Host bookkeeping only — NOT a pickle of the optimizer (param_dict
    holds live Parameters; the arrays are captured separately)."""
    return {
        "num_update": opt.num_update,
        "index_update_count": dict(opt._index_update_count),
        "attrs": {a: getattr(opt, a) for a in _OPT_SCALAR_ATTRS
                  if hasattr(opt, a)},
        "sched": (pickle.dumps(opt.lr_scheduler,
                               protocol=pickle.HIGHEST_PROTOCOL)
                  if opt.lr_scheduler is not None else None),
    }


def _opt_restore(opt, snap):
    opt.num_update = snap["num_update"]
    # in place: _all_index_update_counts[0] aliases this dict
    opt._index_update_count.clear()
    opt._index_update_count.update(snap["index_update_count"])
    for a, v in snap["attrs"].items():
        setattr(opt, a, v)
    if snap["sched"] is not None and opt.lr_scheduler is not None:
        clone = pickle.loads(snap["sched"])
        opt.lr_scheduler.__dict__.update(clone.__dict__)


def restore_fused_step_state(fused, tree):
    """Inverse of `fused_step_state` — writes the snapshot back in place
    (the jitted programs keep their captured NDArray objects)."""
    import jax.numpy as jnp
    from ..gluon.fused_step import _state_write

    def dev(x):
        return None if x is None else jnp.asarray(x)

    for p, raw in zip(fused._train_nds, tree["train"]):
        p._write(dev(raw))
    for p, raw in zip(fused._other_nds, tree["other"]):
        p._write(dev(raw))

    def dev_tree(x):
        if x is None:
            return None
        if isinstance(x, tuple):
            return tuple(dev_tree(e) for e in x)
        return dev(x)

    for s, raws in zip(fused._states, tree["states"]):
        _state_write(s, dev_tree(raws))
    _opt_restore(fused._trainer._optimizer, tree["optimizer"])
    # the host scalar cache (lr/t schedules) is stale for the rewound counts
    fused._scal_cache = None
    _rng_restore(tree["rng"])
