"""Deterministic fault injection for comm and train-step call sites.

Every recovery path in this framework must be testable on one chip, with no
fleet and no luck involved. The instrumented hot paths call
``faults.check(site)``; when a fault plan is active and one of its entries
matches (site, nth-call-at-that-site), the harness injects the fault.

Instrumented sites:

``kvstore.push`` / ``kvstore.pull``   per-key store traffic (local + dist)
``collective.all_reduce`` / ``collective.barrier``   eager collectives
``collective.reduce_scatter`` / ``collective.all_gather``  ZeRO comm legs
``train.step``                        inside the fused/sharded step
``run.step``                          the runner's pre-mutation boundary
``dist.initialize``                   coordinator rendezvous
``checkpoint.save``                   AFTER the step payload is durable,
                                      BEFORE the LATEST marker moves — an
                                      injected crash here IS the
                                      "crashed mid-commit a step ahead"
                                      scenario the commit election guards
                                      against (SnapshotCheckpointer and
                                      the orbax path both carry it)
``checkpoint.restore``                on the way into a restore
``preempt.poll``                      the maintenance-event poller; a
                                      ``preempt`` fault here simulates a
                                      TPU-VM preemption NOTICE (proactive
                                      checkpoint), not a crash
``serve.admit``                       inference-request admission
                                      (`mx.serve.InferenceServer.submit`)
``serve.step``                        top of every serving scheduler step
                                      (inside the watchdog guard, before
                                      admission/decode) — an ``error`` or
                                      ``preempt`` here IS the
                                      "replica killed mid-stream" drill:
                                      in-flight streams drain back to the
                                      queue and resume by re-prefill
``train.batch``                       the runner adapters' batch fetch
                                      (a `transform` site: a ``corrupt``
                                      here poisons one batch with NaN —
                                      the divergence-sentinel drill)
``checkpoint.corrupt``                the SnapshotCheckpointer's payload
                                      bytes, between pickling and the
                                      atomic write (a `transform` site: a
                                      ``corrupt`` here flips bytes ON DISK
                                      while the sha256 sidecar keeps the
                                      true digest — the torn-disk drill)

Fault kinds:

``error``    raise `InjectedFault` (a TransportError — retriable)
``latency``  sleep `arg` seconds, then continue (models a slow endpoint)
``hang``     sleep in small cooperative ticks for `arg` seconds (default
             3600 — "forever" at test scale). The tick loop gives the hang
             watchdog's asynchronous `StallError` a bytecode boundary to
             land on, exactly like a Python-level wait on a dead collective.
``preempt``  raise `PreemptionError` (models host preemption — the runner
             restores a checkpoint instead of retrying in place)
``corrupt``  silently mutate the payload passing through a `transform`
             site: NaN into the first float array, XOR-flipped bytes into a
             bytes blob. Models the failures that DON'T raise — a bad batch,
             a flaky DMA, a torn disk write. At plain `check` sites (no
             payload to mutate) a ``corrupt`` spec is a no-op.

Plans come from the ``MXNET_TPU_FAULT_PLAN`` env var or a context manager::

    MXNET_TPU_FAULT_PLAN="kvstore.push:error:1;run.step:preempt:4"

    with faults.inject("collective.all_reduce:latency:2:0.05"):
        ...

Entry grammar: ``site:kind:nth[:arg]`` joined by ``;``. ``nth`` is the
1-based call count at that site (each retry re-enters the site and counts
again — so ``error:1`` fails the first attempt and lets the retry through,
which is precisely the "retry succeeds" scenario). ``nth`` may also be
``N+`` (every call from the Nth on) or ``*`` (every call).

When no plan is active ``check()`` is one global ``is None`` test — the
instrumented paths pay nothing in production.
"""
from __future__ import annotations

import os
import threading
import time

from .errors import InjectedFault, PreemptionError

__all__ = ["FaultSpec", "FaultPlan", "inject", "activate", "deactivate",
           "active_plan", "check", "transform", "reset_counts",
           "call_count", "HANG_TICK_S"]

KINDS = ("error", "latency", "hang", "preempt", "corrupt")

# cooperative hang granularity: small enough that an async StallError lands
# promptly, large enough to stay off the scheduler's back
HANG_TICK_S = 0.01

# the ONLY state `check` reads when no plan is active
_ACTIVE = None
_LOCK = threading.Lock()


class FaultSpec:
    """One planned fault: (site, kind, nth, arg)."""

    __slots__ = ("site", "kind", "nth", "from_nth_on", "every", "arg")

    def __init__(self, site, kind, nth, arg=None):
        if kind not in KINDS:
            raise ValueError("fault kind must be one of %s, got %r"
                             % (KINDS, kind))
        self.site = site
        self.kind = kind
        nth = str(nth)
        self.every = nth == "*"
        self.from_nth_on = nth.endswith("+")
        self.nth = 0 if self.every else int(nth.rstrip("+"))
        if not self.every and self.nth < 1:
            raise ValueError("fault nth is 1-based, got %r" % (nth,))
        self.arg = arg

    def matches(self, count):
        if self.every:
            return True
        if self.from_nth_on:
            return count >= self.nth
        return count == self.nth

    def __repr__(self):
        nth = "*" if self.every else (
            "%d+" % self.nth if self.from_nth_on else str(self.nth))
        core = "%s:%s:%s" % (self.site, self.kind, nth)
        return core if self.arg is None else "%s:%g" % (core, self.arg)


class FaultPlan:
    """An ordered set of FaultSpecs plus per-site call counters."""

    def __init__(self, specs=()):
        self.specs = list(specs)
        self._counts = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text):
        """Parse the ``site:kind:nth[:arg];...`` grammar (env var format)."""
        specs = []
        for entry in (text or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) not in (3, 4):
                raise ValueError(
                    "fault plan entry %r is not site:kind:nth[:arg]" % entry)
            site, kind, nth = parts[0], parts[1], parts[2]
            arg = float(parts[3]) if len(parts) == 4 else None
            specs.append(FaultSpec(site, kind, nth, arg))
        return cls(specs)

    def bump(self, site):
        with self._lock:
            c = self._counts.get(site, 0) + 1
            self._counts[site] = c
            return c

    def count(self, site):
        return self._counts.get(site, 0)

    def reset_counts(self):
        with self._lock:
            self._counts.clear()

    def match(self, site, count):
        for spec in self.specs:
            if spec.site == site and spec.matches(count):
                return spec
        return None

    def __repr__(self):
        return "FaultPlan(%s)" % ";".join(repr(s) for s in self.specs)


def _plan_from_env():
    text = os.environ.get("MXNET_TPU_FAULT_PLAN", "")
    return FaultPlan.parse(text) if text.strip() else None


def activate(plan=None):
    """Install `plan` (a FaultPlan or plan string) globally; with no
    argument, (re)load from MXNET_TPU_FAULT_PLAN. Returns the active plan
    (None if there is nothing to inject)."""
    global _ACTIVE
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    with _LOCK:
        _ACTIVE = plan if plan is not None else _plan_from_env()
        return _ACTIVE


def deactivate():
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


def active_plan():
    return _ACTIVE


class inject:
    """Context manager scoping a fault plan: the previous plan is restored
    on exit, call counters start fresh on entry."""

    def __init__(self, plan):
        self.plan = (FaultPlan.parse(plan) if isinstance(plan, str)
                     else plan)

    def __enter__(self):
        global _ACTIVE
        with _LOCK:
            self._prev = _ACTIVE
            self.plan.reset_counts()
            _ACTIVE = self.plan
        return self.plan

    def __exit__(self, *exc):
        global _ACTIVE
        with _LOCK:
            _ACTIVE = self._prev
        return False


def reset_counts():
    plan = _ACTIVE
    if plan is not None:
        plan.reset_counts()


def call_count(site):
    plan = _ACTIVE
    return plan.count(site) if plan is not None else 0


def _fire(spec, site, count, context):
    from .. import telemetry as _telem
    if spec.kind == "corrupt":
        # corruption mutates a payload; a plain check() site has none —
        # the spec only bites at transform() sites
        return
    _telem.inc("resilience.faults_injected")
    _telem.inc("resilience.faults_injected.%s" % spec.kind)
    where = "%s (call #%d%s)" % (
        site, count, (", %s" % context) if context else "")
    if spec.kind == "error":
        raise InjectedFault(
            "injected transport fault at %s" % where, site=site)
    if spec.kind == "preempt":
        raise PreemptionError("injected host preemption at %s" % where)
    if spec.kind == "latency":
        time.sleep(spec.arg if spec.arg is not None else 0.05)
        return
    # hang: cooperative tick loop — an async StallError from the watchdog
    # (or plain slow-path completion when nobody is watching) ends it
    deadline = time.monotonic() + (spec.arg if spec.arg is not None
                                   else 3600.0)
    with _telem.span("injected_hang@%s" % site, "fault"):
        while time.monotonic() < deadline:
            time.sleep(HANG_TICK_S)


def check(site, context=None):
    """Fault-injection hook — call at the top of an instrumented site.

    No-op (one global read) when no plan is active. Otherwise counts the
    call and fires any matching planned fault. `context` is a short string
    folded into the injected error message (e.g. "key=conv0_weight").
    """
    plan = _ACTIVE
    if plan is None:
        return
    count = plan.bump(site)
    spec = plan.match(site, count)
    if spec is not None:
        _fire(spec, site, count, context)


# ---------------------------------------------------------------------------
# payload-transforming sites (the ``corrupt`` kind)
# ---------------------------------------------------------------------------
def _corrupt_one(val):
    """Corrupt ONE value; returns (new_val, did_corrupt). NaN into float
    arrays (numpy or NDArray), XOR-flipped bytes into a bytes blob —
    deterministic, so a chaos schedule replays bit-identically."""
    import numpy as _np
    if isinstance(val, (bytes, bytearray)):
        raw = bytes(val)
        if not raw:
            return val, False
        mid = len(raw) // 2
        span = max(1, min(8, len(raw) - mid))
        return (raw[:mid] + bytes(b ^ 0xFF for b in raw[mid:mid + span])
                + raw[mid + span:]), True
    if isinstance(val, _np.ndarray):
        if val.dtype.kind == "f" and val.size:
            out = val.copy()
            out.flat[0] = _np.nan
            return out, True
        return val, False
    if hasattr(val, "asnumpy") and hasattr(val, "context"):  # NDArray
        arr = _np.asarray(val.asnumpy())
        if arr.dtype.kind == "f" and arr.size:
            arr = arr.copy()
            arr.flat[0] = _np.nan
            from ..ndarray import array as _nd_array
            return _nd_array(arr, ctx=val.context, dtype=val.dtype), True
        return val, False
    # jax arrays (the raw device payloads) take the same NaN poke
    dt = getattr(val, "dtype", None)
    if dt is not None and hasattr(val, "at") and getattr(val, "size", 0):
        try:
            if _np.dtype(dt).kind == "f":
                import jax.numpy as jnp
                return val.at[(0,) * val.ndim].set(jnp.nan), True
        except TypeError:
            pass
    return val, False


def _corrupt_payload(payload):
    """Corrupt the FIRST corruptible element of `payload` (recursing into
    tuples/lists), preserving the container shape."""
    if isinstance(payload, (tuple, list)):
        out = list(payload)
        for i, item in enumerate(out):
            new, did = _corrupt_payload(item)
            if did:
                out[i] = new
                return type(payload)(out), True
        return payload, False
    return _corrupt_one(payload)


def transform(site, payload, context=None):
    """Fault hook for sites where data passes THROUGH: returns `payload`,
    possibly corrupted. Counts the site like `check` and fires non-corrupt
    kinds (error/preempt raise, latency/hang delay) exactly the same; a
    matching ``corrupt`` spec mutates the payload instead — NaN into the
    first float array, flipped bytes into a bytes blob. No-op (one global
    read) when no plan is active."""
    plan = _ACTIVE
    if plan is None:
        return payload
    count = plan.bump(site)
    spec = plan.match(site, count)
    if spec is None:
        return payload
    if spec.kind != "corrupt":
        _fire(spec, site, count, context)
        return payload
    new, did = _corrupt_payload(payload)
    if did:
        from .. import telemetry as _telem
        _telem.inc("resilience.faults_injected")
        _telem.inc("resilience.faults_injected.corrupt")
        _telem.inc("resilience.faults_injected.corrupt.%s" % site)
    return new


# load any env-provided plan at import so `MXNET_TPU_FAULT_PLAN=... python
# train.py` works with zero code changes
activate(_plan_from_env())
