"""Proactive preemption notices: SIGTERM + maintenance-event polling.

The reactive recovery path (restore-and-replay after `PreemptionError`)
throws away every step since the last periodic snapshot. But real TPU-VM
preemptions are *announced*: the fleet manager sends SIGTERM with a grace
window, and the metadata server exposes a pending ``maintenance-event``
before the host disappears. This module turns those announcements into a
`PreemptionNotice` the `ResilientRunner` observes at the next step
boundary, checkpoints **immediately** (a coordinated, off-cadence save),
and only then lets the preemption take the host — resume replays zero
steps instead of up to ``ckpt_every - 1``.

Two notice sources, both optional:

* **SIGTERM** — ``listener.start()`` installs a handler (main thread only;
  silently skipped elsewhere) that records the notice and chains to any
  previous handler. The runner's next boundary check sees it.
* **maintenance poller** — a daemon thread calls ``poll_fn()`` every
  ``MXNET_TPU_PREEMPT_POLL_S`` seconds (default 5). The default poll is
  metadata-server shaped: it consults the deterministic fault plan first
  (an ``MXNET_TPU_FAULT_PLAN`` entry at site ``preempt.poll`` with kind
  ``preempt`` simulates a maintenance event — every proactive path is
  testable on one chip), then, when ``MXNET_TPU_PREEMPT_METADATA_URL`` is
  set, GETs it with a short timeout and treats any body other than
  ``NONE`` as a pending event (the TPU-VM
  ``.../instance/maintenance-event`` contract). Custom fabrics inject
  their own ``poll_fn``.

Telemetry: ``resilience.preempt.notices`` (+ per-source) counters.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time

__all__ = ["PreemptionNotice", "PreemptionListener", "default_poll",
           "default_poll_interval_s", "default_grace_s", "POLL_SITE"]

_LOG = logging.getLogger("mxnet_tpu.resilience")

# the fault-injection site the default poller consults: a plan entry
# "preempt.poll:preempt:N" makes the Nth poll observe a maintenance event
POLL_SITE = "preempt.poll"


def default_poll_interval_s():
    try:
        return float(os.environ.get("MXNET_TPU_PREEMPT_POLL_S", "5"))
    except (TypeError, ValueError):
        return 5.0


def default_grace_s():
    """The announced grace window: how long after the notice the host is
    expected to survive. TPU-VM maintenance SIGTERMs give ~30 s; override
    with MXNET_TPU_PREEMPT_GRACE_S for other fabrics."""
    try:
        return float(os.environ.get("MXNET_TPU_PREEMPT_GRACE_S", "30"))
    except (TypeError, ValueError):
        return 30.0


class PreemptionNotice:
    """One pending preemption announcement, with the hard deadline it
    implies (`received_at + grace`) — the runner budgets its proactive
    checkpoint against `remaining_s()`."""

    __slots__ = ("reason", "source", "received_at", "deadline")

    def __init__(self, reason, source, grace_s=None):
        self.reason = reason
        self.source = source          # "sigterm" | "poll" | custom
        self.received_at = time.time()
        if grace_s is None:
            grace_s = default_grace_s()
        self.deadline = self.received_at + float(grace_s)

    def remaining_s(self):
        """Seconds left in the announced grace window (can go negative)."""
        return self.deadline - time.time()

    def __repr__(self):
        return "PreemptionNotice(%r, source=%r)" % (self.reason, self.source)


def _poll_metadata(url):
    """GET the maintenance-event URL (TPU-VM metadata contract): any body
    other than NONE means the host is going away. Short timeout — a slow
    metadata server must not stall the poll thread's cadence."""
    import urllib.request
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(req, timeout=2.0) as resp:
        body = resp.read().decode("utf-8", "replace").strip()
    if body and body.upper() != "NONE":
        return body
    return None


def default_poll():
    """The pluggable poller's default: fault plan first (deterministic
    tests), then the metadata server when configured. Returns a reason
    string when a maintenance event is pending, else None."""
    from . import faults
    from .errors import PreemptionError
    try:
        faults.check(POLL_SITE)
    except PreemptionError as exc:
        return str(exc)
    url = os.environ.get("MXNET_TPU_PREEMPT_METADATA_URL")
    if url:
        try:
            return _poll_metadata(url)
        except Exception as exc:  # noqa: BLE001 - metadata flakiness is not
            # a preemption; keep polling
            _LOG.debug("preempt: metadata poll failed: %s", exc)
    return None


class PreemptionListener:
    """Collects preemption notices from SIGTERM and a maintenance poller.

    Usage (the runner does this when handed a listener)::

        listener = PreemptionListener()
        listener.start()
        ...
        notice = listener.pending()    # at each step boundary
        ...
        listener.stop()

    Thread model: `notify`/`pending`/`clear` serialize on one lock; the
    poll thread and a signal handler may race a main-thread reader.
    """

    def __init__(self, poll_fn=None, poll_interval_s=None, sigterm=True,
                 on_notice=None, grace_s=None):
        # poll_fn: None = the default (fault plan + metadata server),
        # False = signal-only listener, callable = custom fabric
        if poll_fn is None:
            poll_fn = default_poll
        elif poll_fn is False:
            poll_fn = None
        self._poll_fn = poll_fn
        self.grace_s = (default_grace_s() if grace_s is None
                        else float(grace_s))
        self._poll_interval_s = (default_poll_interval_s()
                                 if poll_interval_s is None
                                 else float(poll_interval_s))
        self._sigterm = sigterm
        self._on_notice = on_notice
        self._lock = threading.Lock()
        self._notice = None
        # set by the SIGTERM handler WITHOUT locks or telemetry (signal
        # handlers run on the main thread between bytecodes — taking
        # self._lock there deadlocks if the interrupted frame holds it);
        # pending() and the poll thread fold it into a real notice from
        # normal context
        self._sig_reason = None
        self._stop_event = threading.Event()
        self._thread = None
        self._prev_handler = None
        self._installed = False

    # ------------------------------------------------------------------
    def start(self):
        """Install the SIGTERM handler (main thread only) and start the
        poll thread. Idempotent."""
        if self._sigterm and not self._installed:
            try:
                self._prev_handler = signal.signal(
                    signal.SIGTERM, self._handle_sigterm)
                self._installed = True
            except ValueError:
                # not the main thread: poller-only mode
                _LOG.debug("preempt: SIGTERM handler skipped (not main "
                           "thread)")
        if self._poll_fn is not None and (
                self._thread is None or not self._thread.is_alive()):
            self._stop_event.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, name="mxnet_tpu_preempt_poll",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Stop polling and restore the previous SIGTERM handler."""
        self._stop_event.set()
        thread = self._thread
        if thread is not None and thread.is_alive() and \
                thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self._thread = None
        if self._installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler
                              if self._prev_handler is not None
                              else signal.SIG_DFL)
            except ValueError:  # pragma: no cover - stop() off-main-thread
                pass
            self._installed = False

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    def notify(self, reason, source):
        """Record a notice (first one wins; later sources are counted but
        do not overwrite the original deadline)."""
        from .. import telemetry as _telem
        _telem.inc("resilience.preempt.notices")
        _telem.inc("resilience.preempt.notices.%s" % source)
        with self._lock:
            if self._notice is not None:
                return self._notice
            notice = PreemptionNotice(reason, source, grace_s=self.grace_s)
            self._notice = notice
        _LOG.warning("preempt: %s notice — %s (checkpointing at the next "
                     "step boundary)", source, reason)
        if self._on_notice is not None:
            try:
                self._on_notice(notice)
            except Exception:  # noqa: BLE001 - callbacks must not kill us
                pass
        return notice

    def pending(self):
        self._fold_signal()
        with self._lock:
            return self._notice

    def clear(self):
        with self._lock:
            notice, self._notice = self._notice, None
        return notice

    def _fold_signal(self):
        """Convert a signal-context flag into a real notice from normal
        context (where locks and telemetry are safe)."""
        reason, self._sig_reason = self._sig_reason, None
        if reason is not None:
            self.notify(reason, "sigterm")

    # ------------------------------------------------------------------
    def _handle_sigterm(self, signum, frame):
        # signal context: a single attribute store only (atomic under the
        # GIL) — no locks, no telemetry, both of which could be held by
        # the very frame this handler interrupted
        self._sig_reason = "SIGTERM received"
        prev = self._prev_handler
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    def _poll_loop(self):
        while not self._stop_event.is_set():
            if self.pending() is not None:  # also folds a SIGTERM flag
                return  # one notice is terminal; the host is going away
            try:
                reason = self._poll_fn()
            except Exception as exc:  # noqa: BLE001 - a poller bug must not
                # kill the listener thread
                _LOG.debug("preempt: poll_fn raised: %s", exc)
                reason = None
            if reason:
                self.notify(str(reason), "poll")
                return  # one notice is terminal; the host is going away
            self._stop_event.wait(self._poll_interval_s)
