"""Coordinated commit: fleet-wide agreement on the latest committed step.

A pod run has one checkpoint stream per rank (a `SnapshotCheckpointer` on
each host's local disk, or per-host shards of one orbax step dir). Without
coordination, a rank that crashes *mid-commit* leaves the fleet disagreeing
about which step is "latest": the crashed rank's disk says N+1, everyone
else says N, and a naive restore replays different steps on different ranks
— the collectives that follow deadlock or, worse, silently mix step-N and
step-N+1 parameters.

The fix is a two-phase protocol, the dynamic analog of a distributed
transaction commit:

1. **prepare** — every rank makes its snapshot for step N durable
   (`SnapshotCheckpointer.prepare` / orbax save). The LATEST marker does
   NOT move yet; a crash here is harmless (the payload is invisible).
2. **elect + commit** — every rank reports its newest *durable* step and
   the fleet elects the **minimum** over the `jax.distributed` coordinator
   (KV store + barrier). Only then does each rank flip its LATEST marker —
   to the *elected* step, which every rank is guaranteed to have. Restore
   runs the same election over the ranks' newest committed steps, so even
   a rank that died between prepare and commit rejoins at the step the
   rest of the fleet agreed on.

`CommitCoordinator.elect(step)` is the election; it is a collective (every
rank must call it in lockstep, like a barrier). Single-process runs elect
trivially (the step itself), so the protocol costs nothing off-pod. For
unit tests the fleet exchange is injectable via ``gather=`` — hand it a
callable returning every rank's step and the election logic is testable on
one process.

Election rounds write per-rank keys into the coordinator KV store; a
months-long run with a checkpoint every few minutes would grow that store
without bound. After every KV election the coordinator therefore runs a
**cleanup round**: it deletes this rank's keys from every *earlier* round
it wrote — including rounds whose own election died in the barrier (a
flaky coordinator must not leak one key per failed election). Lockstep
makes that safe: a rank passes round N's barrier only after every rank
entered round N, i.e. finished reading every earlier round, so no reader
can still want a deleted key. Steady state is ≤ 2 rounds of keys live
per rank, regardless of run length.

Telemetry: ``resilience.commit.elections`` counts rounds,
``resilience.commit.rank_ahead`` counts rounds where THIS rank had
prepared past the elected step (the mid-commit-crash shape),
``resilience.commit.cleanups`` counts reclaimed rounds, and the
``resilience.commit.elected_step`` gauge tracks the agreed frontier.
"""
from __future__ import annotations

import logging
import threading

__all__ = ["CommitCoordinator", "elect_step"]

_LOG = logging.getLogger("mxnet_tpu.resilience")

# one election namespace per process; the round counter makes coordinator
# KV keys unique across successive elections (ranks call elect in lockstep,
# so a local counter stays globally consistent)
_ROUND_LOCK = threading.Lock()
_ROUND = [0]


def _next_round():
    with _ROUND_LOCK:
        _ROUND[0] += 1
        return _ROUND[0]


def _coordinator_client():
    """The jax.distributed coordination-service client, or None when this
    process never rendezvoused (single-process run)."""
    from ..parallel.dist import coordinator_client
    return coordinator_client()


def _num_processes():
    import jax
    try:
        return jax.process_count()
    except Exception:  # pragma: no cover - backend not initialized
        return 1


class CommitCoordinator:
    """Min-step election over the multi-controller runtime.

    gather:     override the fleet exchange — ``gather(step, round_id) ->
                list[int]`` of every rank's step (testing / custom fabrics).
    timeout_s:  per-phase coordinator deadline. A rank that dies before the
                barrier surfaces as a retriable timeout instead of a hang.
    namespace:  KV-store key prefix (two concurrent checkpoint streams in
                one job must not share election rounds).
    """

    def __init__(self, gather=None, timeout_s=60.0,
                 namespace="mxnet_tpu.commit"):
        self._gather = gather
        self.timeout_s = float(timeout_s)
        self.namespace = namespace
        # (kind, round_id) of every KV round this instance WROTE a key for
        # and has not yet reclaimed. Recorded at write time (not after the
        # reads) so a round whose barrier times out still gets cleaned by
        # the next successful election instead of leaking forever.
        self._cleanup_lock = threading.Lock()
        self._pending_rounds = []

    _PENDING_ROUNDS_CAP = 64  # bound the ledger under a flaky coordinator

    # ------------------------------------------------------------------
    def elect(self, step, kind="save"):
        """Collective: returns the fleet-wide committed step (min over every
        rank's `step`). `step` may be None (nothing durable on this rank
        yet) — the election then returns None only if NO rank has a step.

        `kind` tags telemetry AND namespaces the coordinator keys/barrier:
        every rank must call the same sequence of elections in lockstep
        (on a pod the faults that trigger restore elections are fleet-wide
        — a dead collective fails on every rank — so lockstep holds; a
        rank-local skew, e.g. SIGTERM delivered to one host only, makes a
        save-election and a restore-election meet at DIFFERENT barrier ids
        and surface as a loud coordinator timeout instead of silently
        electing across mismatched rounds)."""
        from .. import telemetry as _telem
        round_id = _next_round()
        steps = self._exchange(step, kind, round_id)
        present = [s for s in steps if s is not None]
        elected = min(present) if present else None
        _telem.inc("resilience.commit.elections")
        _telem.inc("resilience.commit.elections.%s" % kind)
        if elected is not None:
            _telem.set_gauge("resilience.commit.elected_step", elected)
            if step is not None and step > elected:
                # this rank prepared past the fleet frontier — exactly the
                # crashed-mid-commit shape the protocol guards against
                _telem.inc("resilience.commit.rank_ahead")
                _LOG.warning(
                    "commit: this rank prepared step %s but the fleet "
                    "elected %s — committing the elected step", step, elected)
        return elected

    # ------------------------------------------------------------------
    def _exchange(self, step, kind, round_id):
        if self._gather is not None:
            return list(self._gather(step, round_id))
        if _num_processes() <= 1:
            return [step]
        client = _coordinator_client()
        if client is not None:
            try:
                return self._exchange_kv(client, step, kind, round_id)
            except Exception as exc:  # noqa: BLE001 - fall through to DCN
                _LOG.warning("commit: coordinator KV election failed (%s); "
                             "falling back to allgather", exc)
        return self._exchange_allgather(step)

    def _exchange_kv(self, client, step, kind, round_id):
        """Election over the coordination service: set rank keys, barrier,
        read every rank's key. The barrier guarantees all writes landed;
        `kind` in the barrier id makes mis-paired election sequences
        (one rank saving, another restoring) time out loudly."""
        import jax
        rank = jax.process_index()
        num = jax.process_count()
        prefix = "%s/%s/round_%d" % (self.namespace, kind, round_id)
        timeout_ms = int(self.timeout_s * 1000)
        client.key_value_set("%s/rank_%d" % (prefix, rank),
                             "none" if step is None else str(int(step)))
        with self._cleanup_lock:
            # recorded BEFORE the barrier: a round that dies in the
            # barrier/reads below still gets reclaimed by the next
            # successful election
            self._pending_rounds.append((kind, round_id))
            del self._pending_rounds[:-self._PENDING_ROUNDS_CAP]
        client.wait_at_barrier("%s/barrier" % prefix, timeout_ms)
        steps = []
        for r in range(num):
            raw = client.blocking_key_value_get(
                "%s/rank_%d" % (prefix, r), timeout_ms)
            steps.append(None if raw == "none" else int(raw))
        self.cleanup_round(client, rank, kind, round_id)
        return steps

    def cleanup_round(self, client, rank, kind, round_id):
        """Reclaim this rank's keys from every earlier round it wrote
        (including rounds whose election failed mid-way). Safe because we
        run AFTER passing the CURRENT round's barrier: a rank can only be
        at that barrier once every rank entered this round, i.e. finished
        reading every earlier round — the deletes can race nothing.
        Best-effort: a coordinator without delete support just grows (the
        pre-cleanup behavior), it does not fail the checkpoint; failed
        deletes stay on the ledger for the next election. Returns the
        number of rounds reclaimed."""
        from .. import telemetry as _telem
        with self._cleanup_lock:
            stale = [rd for rd in self._pending_rounds
                     if rd != (kind, round_id)]
        reclaimed = []
        for old_kind, old_round in stale:
            key = "%s/%s/round_%d/rank_%d" % (self.namespace, old_kind,
                                              old_round, rank)
            try:
                client.key_value_delete(key)
            except Exception as exc:  # noqa: BLE001 — cleanup must never
                # fail the election that triggered it
                _LOG.debug("commit: cleanup of %s failed: %s", key, exc)
                continue
            reclaimed.append((old_kind, old_round))
            _telem.inc("resilience.commit.cleanups")
        if reclaimed:
            with self._cleanup_lock:
                self._pending_rounds = [
                    rd for rd in self._pending_rounds
                    if rd not in reclaimed]
        return len(reclaimed)

    @staticmethod
    def _exchange_allgather(step):
        """Fallback fleet exchange over one DCN allgather (the
        telemetry.aggregate mechanism) when the coordination-service client
        is unavailable. None travels as -1."""
        import numpy as _np
        from jax.experimental import multihost_utils
        local = _np.asarray([-1 if step is None else int(step)], _np.int64)
        gathered = _np.asarray(
            multihost_utils.process_allgather(local)).reshape(-1)
        return [None if s < 0 else int(s) for s in gathered]


def elect_step(step, kind="save", timeout_s=60.0):
    """One-shot election with a default coordinator (module-level
    convenience for the checkpoint layers)."""
    return CommitCoordinator(timeout_s=timeout_s).elect(step, kind=kind)
