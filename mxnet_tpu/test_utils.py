"""Test utilities. reference: python/mxnet/test_utils.py — same core idioms
(SURVEY.md §4): dtype-aware assert_almost_equal, finite-difference
check_numeric_gradient, cross-context check_consistency, rand_ndarray,
env-switchable default_context.
"""
from __future__ import annotations

import os

import numpy as np

from .context import Context, cpu, tpu, current_context
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["download", "rand_shape_2d", "rand_shape_3d",
           "rand_sparse_ndarray", "same_symbol_structure", "discard_stderr",
           "default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_shape_nd", "rand_ndarray",
           "random_arrays", "check_numeric_gradient", "numeric_grad",
           "check_consistency", "simple_forward", "default_dtype",
           "effective_dtype", "DummyIter"]

_default_ctx = None

# per-dtype default tolerances (reference: test_utils.py default_tols)
_DEFAULT_RTOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
                 np.dtype(np.float64): 1e-5, np.dtype(np.int64): 0,
                 np.dtype(np.int32): 0, np.dtype(np.uint8): 0}
_DEFAULT_ATOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-5,
                 np.dtype(np.float64): 1e-7, np.dtype(np.int64): 0,
                 np.dtype(np.int32): 0, np.dtype(np.uint8): 0}


def is_accel_test_device():
    """True when the suite is an on-chip run (MXNET_TEST_DEVICE=tpu|gpu).
    Single source of truth — tests/conftest.py re-derives it inline only
    because it must run before any mxnet_tpu/jax import."""
    return (os.environ.get("MXNET_TEST_DEVICE", "cpu").split("(")[0]
            in ("tpu", "gpu"))


def default_context():
    """reference: test_utils.py (default_context) — env-switchable so one
    suite runs on every device type (MXNET_TEST_DEVICE=cpu|tpu)."""
    global _default_ctx
    if _default_ctx is not None:
        return _default_ctx
    dev = os.environ.get("MXNET_TEST_DEVICE")
    if dev:
        name = dev.split("(")[0]
        idx = int(dev.split("(")[1].rstrip(")")) if "(" in dev else 0
        return Context(name, idx)
    return current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def effective_dtype(arr):
    dt = np.dtype(arr.dtype if hasattr(arr, "dtype") else np.float32)
    # bf16 accumulates like fp16 for tolerance purposes
    if dt.name == "bfloat16":
        return np.dtype(np.float16)
    return dt


def _as_numpy(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b):
    return np.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _as_numpy(a), _as_numpy(b)
    rtol, atol = _resolve_tols(a, b, rtol, atol)
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def _resolve_tols(a, b, rtol, atol):
    da, db = effective_dtype(a), effective_dtype(b)
    # the coarser dtype decides (reference: get_tols)
    key = da if _DEFAULT_RTOL.get(da, 0) > _DEFAULT_RTOL.get(db, 0) else db
    if rtol is None:
        rtol = _DEFAULT_RTOL.get(key, 1e-4)
    if atol is None:
        atol = _DEFAULT_ATOL.get(key, 1e-5)
    return rtol, atol


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """reference: test_utils.py (assert_almost_equal) — dtype-aware default
    tolerances, detailed max-error message."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol, atol = _resolve_tols(a_np, b_np, rtol, atol)
    if np.allclose(a_np.astype(np.float64) if a_np.dtype.kind == "f" else a_np,
                   b_np.astype(np.float64) if b_np.dtype.kind == "f" else b_np,
                   rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    err = np.abs(a_np.astype(np.float64) - b_np.astype(np.float64))
    denom = np.abs(b_np.astype(np.float64)) + atol
    rel = err / denom
    idx = np.unravel_index(np.argmax(rel), rel.shape)
    raise AssertionError(
        "Values of %s and %s differ beyond rtol=%g atol=%g: max abs err "
        "%g, max rel err %g at index %s (%r vs %r)"
        % (names[0], names[1], rtol, atol, err.max(), rel.max(), idx,
           a_np[idx], b_np[idx]))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, distribution="uniform"):
    """reference: test_utils.py (rand_ndarray) — dense or sparse random."""
    ctx = ctx or default_context()
    dtype = dtype or np.float32
    if distribution == "normal":
        arr = np.random.standard_normal(size=shape)
    else:
        arr = np.random.uniform(-1.0, 1.0, size=shape)
    if stype in (None, "default"):
        return nd.array(arr.astype(dtype), ctx=ctx)
    density = 0.1 if density is None else density
    mask = np.random.rand(shape[0]) < density if stype == "row_sparse" \
        else np.random.rand(*shape) < density
    if stype == "row_sparse":
        arr = arr * mask.reshape((-1,) + (1,) * (len(shape) - 1))
    elif stype == "csr":
        arr = arr * mask
    else:
        raise ValueError("unknown storage type %s" % stype)
    dense = nd.array(arr.astype(dtype), ctx=ctx)
    return dense.tostype(stype)


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float64) if s else
              np.array(np.random.randn(), dtype=np.float64) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


# --------------------------------------------------------------------------
def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central-difference gradients of executor's scalar-summed output wrt
    every input. reference: test_utils.py (numeric_grad)."""
    grads = {}
    for name, arr in location.items():
        base = arr.copy()
        grad = np.zeros_like(base, dtype=np.float64)
        flat = base.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps / 2
            executor.arg_dict[name][:] = nd.array(base.reshape(arr.shape))
            executor.forward(is_train=use_forward_train)
            f_plus = sum(o.asnumpy().astype(np.float64).sum()
                         for o in executor.outputs)
            flat[i] = orig - eps / 2
            executor.arg_dict[name][:] = nd.array(base.reshape(arr.shape))
            executor.forward(is_train=use_forward_train)
            f_minus = sum(o.asnumpy().astype(np.float64).sum()
                          for o in executor.outputs)
            gflat[i] = (f_plus - f_minus) / eps
            flat[i] = orig
        executor.arg_dict[name][:] = nd.array(base.reshape(arr.shape))
        grads[name] = grad.reshape(arr.shape)
    return grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-4,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, dtype=np.float64):
    """Finite-difference Jacobian vs autograd for a Symbol. reference:
    test_utils.py (check_numeric_gradient) — THE op-test harness
    (SURVEY.md §4: port first)."""
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    location = {k: np.asarray(v, dtype=dtype) for k, v in location.items()}
    if grad_nodes is None:
        grad_nodes = list(location.keys())

    args = {k: nd.array(v, ctx=ctx) for k, v in location.items()}
    args_grad = {k: nd.zeros(v.shape, ctx=ctx)
                 for k, v in location.items() if k in grad_nodes}
    aux = {k: nd.array(np.asarray(v)) for k, v in (aux_states or {}).items()}
    executor = sym.bind(ctx, args=args, args_grad=args_grad,
                        aux_states=aux or None)
    executor.forward(is_train=use_forward_train)
    out = executor.outputs
    out_grads = [nd.ones(o.shape, ctx=ctx) for o in out]
    executor.backward(out_grads)
    sym_grads = {k: v.asnumpy().astype(np.float64)
                 for k, v in executor.grad_dict.items() if v is not None}

    num_grads = numeric_grad(executor, {k: location[k] for k in grad_nodes},
                             aux_states, eps=numeric_eps,
                             use_forward_train=use_forward_train)
    for name in grad_nodes:
        assert_almost_equal(num_grads[name], sym_grads[name], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("numeric_%s" % name, "autograd_%s" % name))


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, rtol=None, atol=None):
    """Run the same symbol on several (ctx, dtype) combos and compare all
    outputs/grads against the highest-precision run. reference:
    test_utils.py (check_consistency)."""
    assert len(ctx_list) > 1
    results = []
    base_loc = None
    for spec in ctx_list:
        ctx = spec["ctx"]
        type_dict = spec.get("type_dict", {})
        shapes = {k: v for k, v in spec.items()
                  if k not in ("ctx", "type_dict")}
        arg_names = sym.list_arguments()
        if base_loc is None:
            base_loc = {}
            for name in arg_names:
                shape = shapes.get(name)
                if shape is None:
                    continue
                base_loc[name] = np.random.normal(size=shape) * scale
        args = {}
        for name in arg_names:
            if name not in base_loc:
                continue
            dt = type_dict.get(name, np.float32)
            args[name] = nd.array(base_loc[name].astype(dt), ctx=ctx)
        args_grad = {k: nd.zeros_like(v) for k, v in args.items()} \
            if grad_req != "null" else None
        exe = sym.bind(ctx, args=args, args_grad=args_grad,
                       grad_req=grad_req)
        exe.forward(is_train=grad_req != "null")
        if grad_req != "null":
            exe.backward([nd.ones(o.shape, ctx=ctx) for o in exe.outputs])
        results.append((spec, exe))
    # the highest-precision run is ground truth (reference: check_consistency
    # sorts ctx_list by dtype width)
    def _prec(res):
        spec = res[0]
        dts = [np.dtype(t) for t in spec.get("type_dict", {}).values()]
        return max((d.itemsize for d in dts), default=4)
    ref_spec, ref_exe = max(results, key=_prec)
    for spec, exe in results:
        if exe is ref_exe:
            continue
        for i, (a, b) in enumerate(zip(ref_exe.outputs, exe.outputs)):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=("out%d@%s" % (i, ref_spec["ctx"]),
                                       "out%d@%s" % (i, spec["ctx"])))
        if grad_req != "null":
            for name in ref_exe.grad_dict:
                assert_almost_equal(
                    ref_exe.grad_dict[name], exe.grad_dict[name],
                    rtol=rtol, atol=atol,
                    names=("grad_%s@%s" % (name, ref_spec["ctx"]),
                           "grad_%s@%s" % (name, spec["ctx"])))
    return [exe.outputs[0].asnumpy() for _, exe in results]


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Bind + forward with numpy inputs; returns numpy outputs."""
    ctx = ctx or default_context()
    args = {k: nd.array(np.asarray(v), ctx=ctx) for k, v in inputs.items()}
    exe = sym.bind(ctx, args=args, grad_req="null")
    exe.forward(is_train=is_train)
    outs = [o.asnumpy() for o in exe.outputs]
    return outs if len(outs) > 1 else outs[0]


class DummyIter:
    """Repeat one batch forever. reference: test_utils.py (DummyIter)."""

    def __init__(self, real_iter):
        self._iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(iter(real_iter))

    def __iter__(self):
        return self

    def __next__(self):
        return self.the_batch

    def next(self):
        return self.the_batch

    def reset(self):
        pass


def download(url, fname=None, dirname=None, overwrite=False, retries=5):
    """reference: test_utils.py (download) — thin delegate to
    gluon.utils.download (file:// / local paths only in this offline
    build; a real URL raises with a clear message)."""
    import os as _os
    from .gluon.utils import download as _dl
    path = None
    if dirname is not None:
        _os.makedirs(dirname, exist_ok=True)
        path = _os.path.join(dirname, fname) if fname else dirname
    elif fname is not None:
        path = fname
    return _dl(url, path=path, overwrite=overwrite, retries=retries)


def rand_shape_2d(dim0=10, dim1=10):
    """reference: test_utils.py (rand_shape_2d)."""
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        distribution="uniform"):
    """Random sparse NDArray + its dense numpy mirror.
    reference: test_utils.py (rand_sparse_ndarray) — returns (arr, (data
    tuple)) there; here (arr, dense_np) which is what tests actually use."""
    from .ndarray import sparse as sp
    density = np.random.rand() if density is None else density
    dtype = np.float32 if dtype is None else np.dtype(dtype)
    dense = np.random.rand(*shape).astype(dtype)
    if stype == "row_sparse":
        mask = np.random.rand(shape[0]) < density
        dense[~mask] = 0
        return sp.row_sparse_array(dense), dense
    if stype == "csr":
        mask = np.random.rand(*shape) < density
        dense = dense * mask
        return sp.csr_matrix(dense), dense
    raise ValueError("unknown stype %s" % stype)


def same_symbol_structure(sym1, sym2):
    """True when two symbols have identical graph structure (ops and
    topology; names ignored). reference: test_utils.py
    (same_symbol_structure)."""
    import json as _json
    def skeleton(s):
        g = _json.loads(s.tojson())
        return [(n["op"], [tuple(i[:2]) for i in n.get("inputs", [])])
                for n in g["nodes"]]
    return skeleton(sym1) == skeleton(sym2)


class discard_stderr:
    """Context manager silencing fd-level stderr (reference:
    test_utils.py discard_stderr — used around intentionally-noisy
    calls)."""

    def __enter__(self):
        import os as _os
        import sys as _sys
        _sys.stderr.flush()
        self._fd = _os.dup(2)
        self._null = _os.open(_os.devnull, _os.O_WRONLY)
        _os.dup2(self._null, 2)
        return self

    def __exit__(self, *exc):
        import os as _os
        import sys as _sys
        _sys.stderr.flush()
        _os.dup2(self._fd, 2)
        _os.close(self._null)
        _os.close(self._fd)
        return False
