"""Whole-graph lowering: a bound Symbol graph → ONE compiled XLA program.

The executor's op-by-op loop (`symbol/executor.py`) dispatches every NNVM
node through `nd.invoke` — correct, but each op is its own XLA program and
each call its own Python round-trip. This module walks the graph once,
runs the graph-level pass pipeline (`passes.run_pipeline`), emits a single
pure jax function over the executor's bound arrays, and
`jit(...).lower().compile()`s it:

* **forward** — (args..., aux...) → (head outputs...)
* **forward+backward** — same inputs → (head outputs..., grads for every
  argument whose grad_req wants one), differentiated with `jax.vjp` over
  the whole program using the same ones-cotangent `autograd.backward`
  defaults to (ops with custom VJPs — SoftmaxOutput, the regression heads
  — keep their hand-coded gradients, because those live in the op fns
  themselves);
* **forward+backward w/ head grads** — the rare `backward(out_grads=...)`
  path takes the cotangents as extra program inputs.

Programs are memoized process-wide by (graph hash, mode, input signature),
so N data-parallel executors of the same symbol share ONE executable, and
persisted through the AOT cache (`compiler/cache.py`) so the next process
skips XLA entirely. Telemetry: `compiler.lower_ms` / `compiler.compile_ms`
histograms, `compiler.{lower,compile,program_runs}` counters, pass stats
under `compiler.pass.*`, and every compile lands in the
`telemetry.note_compile` ring tagged `[fresh]` or `[cached]`.
"""
from __future__ import annotations

import time

from .. import telemetry as _telem
from ..ops import registry as _reg
from . import passes as _passes
from .cache import aot_cache, avals_sig, cache_key

__all__ = ["GraphProgram", "UnsupportedGraphError"]

UnsupportedGraphError = _passes.UnsupportedGraphError

# process-wide compiled-program memo: data-parallel executor groups bind
# the same symbol once per device slice — identical signatures must share
# one executable (and one compile) instead of compiling per executor
_MEMO = {}
_MEMO_MAX = 128


def _emit(ir, on_tpu):
    """The whole-graph forward as a pure function of the flat inputs
    (args... then aux...). Registry fns are resolved once, with the same
    best_fn(on_tpu) dispatch `nd.invoke` uses, so specialization is
    identical to the op-by-op path."""
    pos = {name: i for i, name in enumerate(
        list(ir.arg_names) + list(ir.aux_names))}
    node_fns = [None if n.op is None else _reg.get(n.op).best_fn(on_tpu)
                for n in ir.nodes]

    def forward(*flat_inputs):
        vals = [None] * len(ir.nodes)
        for i, node in enumerate(ir.nodes):
            if node.is_const:
                vals[i] = node.const
            elif node.is_var:
                vals[i] = flat_inputs[pos[node.name]]
            else:
                ins = []
                for (j, slot) in node.inputs:
                    v = vals[j]
                    if isinstance(v, (tuple, list)):
                        v = v[slot]
                    ins.append(v)
                vals[i] = node_fns[i](*ins, **node.kwargs)
        outs = []
        for (j, slot) in ir.heads:
            v = vals[j]
            if isinstance(v, (tuple, list)):
                v = v[slot]
            outs.append(v)
        return tuple(outs)

    return forward


class GraphProgram:
    """The compiled whole-graph programs for one bound symbol.

    Built once per Executor (cheap: graph walk + passes); the expensive
    jit/compile happens lazily per (mode, input signature) and is shared
    through the process memo + the persistent AOT cache.
    """

    def __init__(self, symbol, on_tpu=False, label=None):
        t0 = time.perf_counter()
        ir = _passes.from_symbol(symbol)
        ir, stats = _passes.run_pipeline(ir, on_tpu)
        self.ir = ir
        self.stats = stats
        self.on_tpu = on_tpu
        self.graph_hash = _passes.graph_hash(ir)
        self.label = label or (symbol.name or "graph")
        self.n_heads = len(ir.heads)
        self._forward = _emit(ir, on_tpu)
        _telem.inc("compiler.lower")
        _telem.observe("compiler.lower_ms",
                       (time.perf_counter() - t0) * 1e3)
        for k, v in stats.items():
            if k != "ops" and v:
                _telem.inc("compiler.pass.%s" % k, v)

    # ------------------------------------------------------------ modes
    def _fn_for(self, mode, wanted_idx, n_heads_grads):
        import jax
        import jax.numpy as jnp
        forward = self._forward
        if mode == "fwd":
            return forward
        wanted = list(wanted_idx)

        def split(flat):
            def inner(wanted_vals):
                full = list(flat)
                for i, v in zip(wanted, wanted_vals):
                    full[i] = v
                return forward(*full)
            return inner

        if mode == "fwdbwd":
            def fwd_bwd(*flat):
                outs, vjp = jax.vjp(split(flat),
                                    [flat[i] for i in wanted])
                cots = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
                (grads,) = vjp(cots)
                return tuple(outs) + tuple(grads)
            return fwd_bwd

        # mode == "fwdbwd_og": the trailing n_heads_grads inputs are the
        # user-supplied head cotangents
        def fwd_bwd_og(*flat_and_cots):
            flat = flat_and_cots[:-n_heads_grads]
            cots = flat_and_cots[-n_heads_grads:]
            outs, vjp = jax.vjp(split(flat), [flat[i] for i in wanted])
            (grads,) = vjp(tuple(cots))
            return tuple(outs) + tuple(grads)
        return fwd_bwd_og

    # ---------------------------------------------------------- compile
    def compiled(self, mode, raws, wanted_idx=()):
        """The compiled executable for `mode` at the signature of `raws`
        (the already-flat input values). Checks, in order: process memo →
        AOT cache → fresh lower+compile (stored back to both)."""
        import jax
        avals = tuple(jax.ShapeDtypeStruct(r.shape, r.dtype) for r in raws)
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in avals)
        memo_key = (self.graph_hash, mode, sig, tuple(wanted_idx),
                    self.on_tpu)
        hit = _MEMO.get(memo_key)
        if hit is not None:
            return hit
        label = "compiler:%s[%s]" % (self.label, mode)
        key = cache_key(kind="symbol_graph", graph=self.graph_hash,
                        mode=mode, wanted=list(wanted_idx),
                        avals=avals_sig(list(avals)))
        cache = aot_cache()
        compiled = cache.load(key, label)
        if compiled is None:
            n_og = self.n_heads if mode == "fwdbwd_og" else 0
            fn = self._fn_for(mode, wanted_idx, n_og)
            t0 = time.perf_counter()
            lowered = jax.jit(fn).lower(*avals)
            compiled = lowered.compile()
            _telem.inc("compiler.compile")
            _telem.observe("compiler.compile_ms",
                           (time.perf_counter() - t0) * 1e3)
            _telem.note_compile(label + "[fresh]")
            from ..telemetry import ledger as _ledger
            footprint = _ledger.harvest(compiled)
            _ledger.note_program(label, footprint)
            meta = {"graph": self.graph_hash, "mode": mode}
            if footprint:
                # stored in the entry so a warm restore (cache.load)
                # replays the footprint without recompiling
                meta["memory_analysis"] = footprint
            cache.store(key, compiled, label, meta=meta)
        if len(_MEMO) >= _MEMO_MAX:
            _MEMO.clear()
        _MEMO[memo_key] = compiled
        return compiled

    # -------------------------------------------------------------- run
    def run_forward(self, raws):
        """One program dispatch: head outputs as a tuple of raw arrays."""
        ex = self.compiled("fwd", raws)
        _telem.inc("compiler.program_runs")
        return ex(*raws)

    def run_fwd_bwd(self, raws, wanted_idx, head_cots=None):
        """Heads + gradients in one dispatch. `wanted_idx` indexes the
        flat inputs whose gradient the executor wants; `head_cots`
        (optional) are user out_grads — without them the program bakes the
        ones-cotangent `autograd.backward` uses."""
        if head_cots is None:
            ex = self.compiled("fwdbwd", raws, wanted_idx)
            out = ex(*raws)
        else:
            flat = tuple(raws) + tuple(head_cots)
            ex = self.compiled("fwdbwd_og", flat, wanted_idx)
            out = ex(*flat)
        _telem.inc("compiler.program_runs")
        return out[:self.n_heads], out[self.n_heads:]
