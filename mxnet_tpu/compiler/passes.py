"""Graph-level optimization passes over a Symbol graph.

The reference executes NNVM graphs op-by-op (graph_executor.cc); Relay
(PAPERS.md) showed the win of lowering the *whole* framework graph to one
IR program and optimizing at graph level before the tensor compiler sees
it, with TVM as the catalog of passes worth running. This module is that
front end: a `Symbol` DAG lowers to a flat SSA-ish `GraphIR`, then

* **constant folding** — nodes whose inputs are all literal constants are
  evaluated once at lower time (eagerly, with the same registry fns the
  op-by-op executor dispatches, so folded values are bit-identical to what
  the eager path would compute);
* **common-subexpression elimination** — structurally identical op nodes
  (same op, same canonicalized hyper-params, same input value-slots)
  merge into one;
* **dead-node elimination** — nodes unreachable from the heads after
  folding/CSE are dropped.

XLA would eventually do some of this per-fusion-island, but running it at
graph level shrinks the traced program (fewer primitives to lower, smaller
HLO to hash for the AOT cache key) and is where layout planning and future
graph rewrites belong.

Anything the pipeline cannot express raises `UnsupportedGraphError` with a
machine-readable reason — the executor counts it and falls back to op-by-op
dispatch, never erroring.
"""
from __future__ import annotations

import hashlib
import json

from ..ops import registry as _reg

__all__ = ["GraphIR", "Node", "UnsupportedGraphError", "from_symbol",
           "fold_constants", "eliminate_common_subexpr",
           "eliminate_dead_nodes", "run_pipeline", "graph_hash"]


class UnsupportedGraphError(Exception):
    """Graph contains something the whole-graph pipeline does not lower.

    `reason` is a short machine-readable slug (`random_op:Dropout`,
    `unknown_op:Custom`, ...) used for the counted-fallback telemetry
    (`compiler.fallback.<reason>`)."""

    def __init__(self, reason):
        super().__init__(reason)
        self.reason = reason


class Node:
    """One IR node. Exactly one of the three kinds:

    * variable (`op is None`, `const is None`): a graph input, fed by
      position from the executor's bound arrays;
    * constant (`op is None`, `const` set): a literal or folded value;
    * op node (`op` set): `inputs` is a list of (producer_index, slot)
      pairs into the IR's node list.
    """

    __slots__ = ("op", "name", "inputs", "kwargs", "num_outputs", "const",
                 "is_aux")

    def __init__(self, op, name, inputs=(), kwargs=None, num_outputs=1,
                 const=None, is_aux=False):
        self.op = op
        self.name = name
        self.inputs = list(inputs)
        self.kwargs = dict(kwargs or {})
        self.num_outputs = num_outputs
        self.const = const
        self.is_aux = is_aux

    @property
    def is_var(self):
        return self.op is None and self.const is None

    @property
    def is_const(self):
        return self.op is None and self.const is not None

    def __repr__(self):
        if self.is_var:
            return "Var(%s)" % self.name
        if self.is_const:
            return "Const(%s)" % self.name
        return "Op(%s:%s)" % (self.op, self.name)


class GraphIR:
    """Topologically ordered node list + output heads."""

    def __init__(self, nodes, heads, arg_names, aux_names):
        self.nodes = nodes            # list[Node], producers before users
        self.heads = heads            # list[(node_index, slot)]
        self.arg_names = arg_names    # positional input order (args...)
        self.aux_names = aux_names    # ...then aux states

    def n_ops(self):
        return sum(1 for n in self.nodes if n.op is not None)


def _literal_const(sym_node):
    """Materialize a literal variable's value the same way the eager
    executor does (`Symbol._literal_value`), but as raw jax values.
    Python-float literals stay python floats: the eager path feeds the op
    a float too, and jax's weak-type promotion must match bit for bit."""
    import ast

    import jax.numpy as jnp
    a = sym_node._attrs
    if "__literal__" in a:
        return float(a["__literal__"])
    if "__literal_zeros__" in a:
        return jnp.zeros(ast.literal_eval(a["__literal_zeros__"]),
                         dtype=jnp.float32)
    if "__literal_ones__" in a:
        return jnp.ones(ast.literal_eval(a["__literal_ones__"]),
                        dtype=jnp.float32)
    if "__literal_arange__" in a:
        start, stop, step = ast.literal_eval(a["__literal_arange__"])
        return jnp.arange(start, stop, step, dtype=jnp.float32)
    return None


def from_symbol(symbol):
    """Lower a Symbol DAG to GraphIR, or raise UnsupportedGraphError."""
    from ..symbol.symbol import _parse_attr
    topo = symbol._topo()
    index = {}
    nodes = []
    for n in topo:
        if n._op == "_group":
            continue  # structural: heads are resolved through _heads()
        if n._op is None:
            if n._is_literal():
                const = _literal_const(n)
                node = Node(None, n._name, const=const)
            else:
                node = Node(None, n._name, is_aux=n._is_aux())
        else:
            try:
                op = _reg.get(n._op)
            except KeyError:
                raise UnsupportedGraphError("unknown_op:%s" % n._op)
            if op.random:
                # the eager path draws per-op keys from the global key
                # table; a whole-graph program cannot replay that draw
                # order bit-identically, so RNG graphs stay op-by-op
                raise UnsupportedGraphError("random_op:%s" % n._op)
            ins = []
            for i in n._inputs:
                base = i._base_node()
                if id(base) not in index:
                    raise UnsupportedGraphError("disconnected_input:%s"
                                                % i._name)
                ins.append((index[id(base)], i._out_index or 0))
            kwargs = {k: _parse_attr(v) for k, v in n._kwargs.items()}
            node = Node(n._op, n._name, ins, kwargs,
                        num_outputs=n._num_outputs)
        index[id(n)] = len(nodes)
        nodes.append(node)
    heads = []
    for h in symbol._heads():
        base = h._base_node()
        hi = index[id(base)]
        if h._out_index is not None:
            heads.append((hi, h._out_index))
        elif h._num_outputs > 1:
            heads.extend((hi, s) for s in range(h._num_outputs))
        else:
            heads.append((hi, 0))
    return GraphIR(nodes, heads, symbol.list_arguments(),
                   symbol.list_auxiliary_states())


# ---------------------------------------------------------------------------
# passes — each returns (new_ir, n_changed)
# ---------------------------------------------------------------------------
def fold_constants(ir, on_tpu=False):
    """Evaluate op nodes whose inputs are all constants, eagerly, with the
    SAME resolved registry fn the op-by-op executor would dispatch — the
    folded value is bit-identical to what eager execution produces."""
    folded = 0
    for node in ir.nodes:
        if node.op is None:
            continue
        producers = [ir.nodes[j] for j, _ in node.inputs]
        if not producers or not all(p.is_const for p in producers):
            continue
        ins = []
        for (j, slot) in node.inputs:
            v = ir.nodes[j].const
            if isinstance(v, (tuple, list)):
                v = v[slot]
            ins.append(v)
        fn = _reg.get(node.op).best_fn(on_tpu)
        try:
            value = fn(*ins, **node.kwargs)
        except Exception:
            continue  # leave it in the program; XLA folds what it can
        node.op, node.inputs, node.kwargs = None, [], {}
        node.const = value
        folded += 1
    return ir, folded


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


def eliminate_common_subexpr(ir):
    """Merge structurally identical op nodes. Variables stay keyed by
    name; constants are left alone (value comparison on arrays is not
    worth the bytes); op nodes key on (op, canon kwargs, resolved input
    slots) — after remapping, so chains of duplicates collapse in one
    sweep."""
    remap = {}  # old index -> surviving index
    seen = {}
    new_nodes = []
    for i, node in enumerate(ir.nodes):
        if node.op is None:
            remap[i] = len(new_nodes)
            new_nodes.append(node)
            continue
        inputs = [(remap[j], s) for j, s in node.inputs]
        key = (node.op, _hashable(node.kwargs), tuple(inputs))
        hit = seen.get(key)
        if hit is not None:
            remap[i] = hit
            continue
        node.inputs = inputs
        remap[i] = len(new_nodes)
        seen[key] = len(new_nodes)
        new_nodes.append(node)
    merged = len(ir.nodes) - len(new_nodes)
    ir.nodes = new_nodes
    ir.heads = [(remap[j], s) for j, s in ir.heads]
    return ir, merged


def eliminate_dead_nodes(ir):
    """Drop nodes unreachable from the heads. Variable nodes are dropped
    from the node list too — the *positional input signature* (arg_names +
    aux_names) is unchanged, so the executor feeds the same arrays and XLA
    sees unused parameters it drops for free."""
    live = set()
    stack = [j for j, _ in ir.heads]
    while stack:
        j = stack.pop()
        if j in live:
            continue
        live.add(j)
        stack.extend(k for k, _ in ir.nodes[j].inputs)
    if len(live) == len(ir.nodes):
        return ir, 0
    remap = {}
    new_nodes = []
    for i, node in enumerate(ir.nodes):
        if i not in live:
            continue
        node.inputs = [(remap[j], s) for j, s in node.inputs]
        remap[i] = len(new_nodes)
        new_nodes.append(node)
    removed = len(ir.nodes) - len(new_nodes)
    ir.nodes = new_nodes
    ir.heads = [(remap[j], s) for j, s in ir.heads]
    return ir, removed


def run_pipeline(ir, on_tpu=False):
    """fold → CSE → DCE. Returns (ir, stats dict) — the stats land in
    telemetry (`compiler.pass.*`) so `parse_log --compile` can show what
    graph-level work the pipeline actually did."""
    ir, folded = fold_constants(ir, on_tpu)
    ir, merged = eliminate_common_subexpr(ir)
    ir, removed = eliminate_dead_nodes(ir)
    return ir, {"folded": folded, "cse_merged": merged,
                "dce_removed": removed, "ops": ir.n_ops()}


# ---------------------------------------------------------------------------
# signature
# ---------------------------------------------------------------------------
def graph_hash(ir):
    """Content hash of the optimized graph — the graph half of the AOT
    cache key (shapes/dtypes/mesh/versions are folded in by
    `cache.cache_key`). Constants hash by VALUE (bytes for arrays, repr
    for scalars), not just shape/dtype: `_emit` bakes them into the
    program as closure values, so two graphs differing only in constant
    contents must never share a memo slot or cache entry."""
    import numpy as _np
    items = []
    for node in ir.nodes:
        if node.is_var:
            items.append(["var", node.name, bool(node.is_aux)])
        elif node.is_const:
            c = node.const
            if isinstance(c, (int, float)):
                val = repr(c)
            else:
                val = hashlib.sha256(_np.asarray(c).tobytes()).hexdigest()
            items.append(["const", list(getattr(c, "shape", ())),
                          str(getattr(c, "dtype", type(c).__name__)),
                          val])
        else:
            items.append(["op", node.op,
                          sorted((str(k), repr(v))
                                 for k, v in node.kwargs.items()),
                          node.inputs])
    blob = json.dumps([items, ir.heads, ir.arg_names, ir.aux_names],
                      sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()
