"""mx.compiler — whole-graph symbolic compiler + persistent AOT cache.

Two coupled layers (ROADMAP item #2, ISSUE 11):

* `lower` / `passes`: a bound Symbol graph lowers through a graph-level
  pass pipeline (constant folding, CSE, dead-node elimination — the
  Relay/TVM playbook from PAPERS.md) into ONE `lower().compile()`d XLA
  program for the whole forward (and forward+backward), which
  `symbol/executor.py` dispatches instead of its op-by-op loop. Gated by
  `MXNET_TPU_WHOLE_GRAPH` (default on) with a counted, never-erroring
  fallback to op-by-op dispatch (`compiler.fallback.<reason>`).
* `cache`: compiled executables serialize to the `MXNET_TPU_AOT_CACHE`
  directory keyed by graph hash + shapes/dtypes + mesh + jax/library
  versions, with atomic writes, corruption-tolerant loads and keep=N
  eviction — `mx.serve`'s warmup executables and the train-step programs
  ride the same cache, so a fleet replica or a preempted elastic worker
  cold-starts in seconds instead of recompiling (`BENCH=startup` is the
  evidence).
"""
from . import cache, lower, passes
from .cache import AOTCache, aot_cache, cache_key
from .lower import GraphProgram, UnsupportedGraphError
from .passes import (GraphIR, eliminate_common_subexpr, eliminate_dead_nodes,
                     fold_constants, from_symbol, graph_hash, run_pipeline)

__all__ = [
    "cache", "lower", "passes",
    "AOTCache", "aot_cache", "cache_key",
    "GraphProgram", "UnsupportedGraphError",
    "GraphIR", "from_symbol", "fold_constants", "eliminate_common_subexpr",
    "eliminate_dead_nodes", "run_pipeline", "graph_hash",
]
