"""Persistent AOT executable cache.

The fleet cold-start problem (ROADMAP item #2): a thousand serving replicas
— or one elastic worker resuming after preemption — each recompile every
program from scratch at startup, and `telemetry.note_compile` measures the
storm. This module serializes *compiled* executables to disk
(`jax.experimental.serialize_executable`, the PjRt executable-serialization
API underneath jax's own compilation cache) keyed by a tracelint-style
signature (graph/program hash + shapes/dtypes + mesh + jax/library
versions), so the second process skips XLA entirely and loads the binary.

Operational contract, in order of importance:

* **Never errs.** A corrupted, truncated, or version-skewed entry is a
  counted miss (`compiler.cache.corrupt`) followed by a normal recompile —
  a bad cache can cost time, never correctness or a crash.
* **Atomic writes.** Entries land via write-to-temp + `os.replace`, so
  concurrent writers (a fleet warming the same shared directory) are
  last-write-wins and readers never observe a half-written file.
* **Version-keyed.** `key_for` folds jax/jaxlib/library versions, backend
  platform, and device count into every key, so an upgraded worker misses
  instead of loading an executable compiled for a different runtime.
* **Bounded.** keep=N eviction (`MXNET_TPU_AOT_CACHE_KEEP`, oldest-mtime
  first) after every store.

Enabled by pointing `MXNET_TPU_AOT_CACHE` at a directory; off by default
(the cache is a deployment optimization, not a semantic change).

**Trust model.** Entries are pickles (that is what the PjRt
serialization API hands back), and loading one executes it. The sha256
framing detects *corruption* — a torn write, a truncated copy — not
*tampering*: anyone who can write the cache directory can make every
reader run arbitrary code. Point `MXNET_TPU_AOT_CACHE` only at
directories writable solely by principals you already trust to run code
on these machines (the same trust you place in the model checkpoint and
the package itself); never at a world-writable or untrusted-shared path.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time

from .. import telemetry as _telem

__all__ = ["AOTCache", "aot_cache", "cache_key", "hlo_hash",
           "load_or_compile"]

# entry layout: MAGIC + sha256(payload) + payload; the digest makes
# truncation/corruption detection exact rather than "pickle happened to
# throw"
_MAGIC = b"MXAOT1\n"
_SUFFIX = ".aotx"
_DEFAULT_KEEP = 32


def _versions():
    """The runtime identity every key embeds: an executable is only
    portable between processes running the same compiler stack on the
    same topology."""
    import jax
    import jaxlib
    from ..base import __version__ as _mx_version
    try:
        n_dev = jax.device_count()
        platform = jax.devices()[0].platform
    except Exception:  # backend not initialized / unreachable
        n_dev, platform = 0, "unknown"
    return {
        "jax": getattr(jax, "__version__", "?"),
        "jaxlib": getattr(jaxlib, "__version__", "?"),
        "mxnet_tpu": _mx_version,
        "platform": platform,
        "device_count": n_dev,
    }


def _canon(obj):
    """Canonicalize key parts into something json can serialize stably."""
    if isinstance(obj, dict):
        return {str(k): _canon(obj[k]) for k in sorted(obj, key=str)}
    if isinstance(obj, (list, tuple)):
        return [_canon(x) for x in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, bytes):
        return hashlib.sha256(obj).hexdigest()
    return repr(obj)


def cache_key(**parts):
    """Hex digest over canonical-json key parts + the runtime versions."""
    parts["__runtime__"] = _versions()
    blob = json.dumps(_canon(parts), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def avals_sig(tree):
    """Shapes/dtypes of a pytree of arrays/ShapeDtypeStructs, as a
    key-part (paths included so two trees with equal leaves but different
    structure key differently)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return {
        "tree": str(treedef),
        "leaves": [[list(getattr(x, "shape", ())),
                    str(getattr(x, "dtype", type(x).__name__))]
                   for x in leaves],
    }


class AOTCache:
    """One cache directory of serialized executables."""

    def __init__(self, path=None, keep=None):
        from ..base import get_env
        if path is None:
            path = get_env("MXNET_TPU_AOT_CACHE", "") or None
        self.path = path
        if keep is None:
            keep = int(get_env("MXNET_TPU_AOT_CACHE_KEEP", _DEFAULT_KEEP))
        self.keep = keep

    @property
    def enabled(self):
        return bool(self.path)

    # ------------------------------------------------------------- load
    def load(self, key, label="program"):
        """The executable stored under `key`, deserialized and loaded onto
        the current backend — or None (counted miss). Corruption of any
        kind (bad magic, digest mismatch, unpicklable, executable rejected
        by the runtime) is a counted `compiler.cache.corrupt` + miss,
        never an exception."""
        if not self.enabled:
            return None
        fname = os.path.join(self.path, key + _SUFFIX)
        t0 = time.perf_counter()
        try:
            with open(fname, "rb") as f:
                blob = f.read()
        except OSError:
            _telem.inc("compiler.cache.misses")
            return None
        try:
            if not blob.startswith(_MAGIC):
                raise ValueError("bad magic")
            digest = blob[len(_MAGIC):len(_MAGIC) + 64]
            payload = blob[len(_MAGIC) + 64:]
            if hashlib.sha256(payload).hexdigest().encode() != digest:
                raise ValueError("checksum mismatch")
            meta, serialized, in_tree_b, out_tree_b = pickle.loads(payload)
            from jax.experimental import serialize_executable as _se
            loaded = _se.deserialize_and_load(
                serialized, pickle.loads(in_tree_b), pickle.loads(out_tree_b))
        except Exception:
            # a bad entry must cost a recompile, not a crash — count it
            # and treat as a miss (the next store overwrites it)
            _telem.inc("compiler.cache.corrupt")
            _telem.inc("compiler.cache.misses")
            return None
        if isinstance(meta, dict) and meta.get("memory_analysis"):
            # replay the static footprint recorded at compile time: a warm
            # restore reports memory_analysis WITHOUT recompiling (the
            # ledger's fleet cold-start evidence)
            from ..telemetry import ledger as _ledger
            _ledger.note_program(label, meta["memory_analysis"],
                                 cached=True)
        _telem.inc("compiler.cache.hits")
        _telem.observe("compiler.cache.load_ms",
                       (time.perf_counter() - t0) * 1e3)
        _telem.note_compile("%s[cached]" % label)
        return loaded

    # ------------------------------------------------------------ store
    def store(self, key, compiled, label="program", meta=None):
        """Serialize `compiled` (a jax.stages.Compiled) under `key`.
        Atomic (temp + rename): concurrent writers are last-write-wins and
        a reader can never see a partial entry. Returns True on success;
        serialization failures are counted, never raised."""
        if not self.enabled:
            return False
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as _se
            serialized, in_tree, out_tree = _se.serialize(compiled)
            payload = pickle.dumps(
                (dict(meta or {}, label=label, versions=_versions()),
                 serialized, pickle.dumps(in_tree), pickle.dumps(out_tree)))
        except Exception:
            _telem.inc("compiler.cache.serialize_error")
            return False
        blob = _MAGIC + hashlib.sha256(payload).hexdigest().encode() + payload
        fname = os.path.join(self.path, key + _SUFFIX)
        try:
            os.makedirs(self.path, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.path,
                                       suffix=_SUFFIX + ".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, fname)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception:
            _telem.inc("compiler.cache.write_error")
            return False
        _telem.inc("compiler.cache.writes")
        _telem.observe("compiler.cache.store_ms",
                       (time.perf_counter() - t0) * 1e3)
        self._evict()
        return True

    def _evict(self):
        """keep=N retention, oldest mtime first. Unlink races with other
        evicting processes are benign (someone removed it for us)."""
        if self.keep <= 0:
            return
        try:
            entries = []
            for name in os.listdir(self.path):
                if not name.endswith(_SUFFIX):
                    continue
                full = os.path.join(self.path, name)
                try:
                    entries.append((os.path.getmtime(full), full))
                except OSError:
                    continue
            entries.sort()
            for _, full in entries[:-self.keep] if len(entries) > self.keep \
                    else []:
                try:
                    os.unlink(full)
                    _telem.inc("compiler.cache.evictions")
                except OSError:
                    pass
        except OSError:
            pass


def hlo_hash(lowered):
    """sha256 of a lowered program's HLO text — the program half of the
    key for sites (train steps) that key on the exact traced
    computation rather than a graph/geometry signature."""
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


def load_or_compile(key, lower_fn, label, meta=None):
    """The compile-or-restore step every AOT rider shares (whole-graph
    executor, serve warmup, train steps): a warm hit returns
    (restored executable, True) without calling `lower_fn`; a miss
    calls it, compiles, stores, and returns (executable, False).
    Site-specific telemetry (`serve.compile`, `*.aot_restored`, ...)
    stays with the callers — they count different things.

    The miss branch harvests `compiled.memory_analysis()` into the HBM
    ledger AND into the cache entry's meta, so the hit branch (another
    process, a warm restart) replays the same footprint without a
    recompile — see telemetry/ledger.py."""
    from ..telemetry import ledger as _ledger
    cache = aot_cache()
    ex = cache.load(key, label)
    if ex is not None:
        return ex, True
    compiled = lower_fn().compile()
    footprint = _ledger.harvest(compiled)
    _ledger.note_program(label, footprint)
    meta = dict(meta or {})
    if footprint:
        meta["memory_analysis"] = footprint
    cache.store(key, compiled, label, meta=meta)
    return compiled, False


# process-level accessor: one AOTCache per MXNET_TPU_AOT_CACHE value, so
# tests (and long-lived processes) that flip the env var get a fresh view
_GLOBAL = {"path": None, "cache": None}


def aot_cache():
    """The process AOT cache (rebuilt if MXNET_TPU_AOT_CACHE changed)."""
    from ..base import get_env
    path = get_env("MXNET_TPU_AOT_CACHE", "") or None
    if _GLOBAL["cache"] is None or _GLOBAL["path"] != path:
        _GLOBAL["path"] = path
        _GLOBAL["cache"] = AOTCache(path)
    return _GLOBAL["cache"]
