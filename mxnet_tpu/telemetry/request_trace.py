"""Per-request distributed tracing for the serving plane.

`mx.serve`'s aggregate histograms answer "how is the fleet doing"; they
cannot answer "why did THIS request take 900 ms to first token". A
`RequestTrace` is created at enqueue and travels with the request — inside
its `StreamHandle`, so it crosses replica boundaries for free when a
drained stream resumes on a survivor — through admit → KV alloc → bucketed
prefill → every decode step → completion (or shed / deadline / recovery
requeue), recording a span timeline on the shared telemetry trace clock.

The timeline TILES the request's wall-clock by construction: every span
starts where the previous one ended (`mark()` closes the open interval and
advances the cursor), so queue-wait + prefill + decode + recovery account
for the request's entire life — the property the acceptance test asserts
(>= 95 %; the only loss is the final cursor→finish tail, one `mark` wide).
Span names:

* ``queue``           enqueue (or backpressure re-entry) → admission pop
* ``prefill``         pop → first emitted token (KV alloc + bucketed
                      prefill; the TTFT tail the client felt)
* ``decode``          one span per emitted token (inter-token interval —
                      time IN the batch, not just inside the decode
                      program, so slot residency is fully accounted)
* ``recovery.drain``  last activity → the replica fault that drained it
* ``recovery.queue``  requeue → re-admission on this or another replica

On completion the trace `finish()`es: a JSON-able payload snapshot joins
the bounded last-N ring (``MXNET_TPU_SERVE_TRACE_RING``, default 128) the
``/requests`` endpoint serves and `DeadlineExceeded` embeds, and the spans
are replayed into the chrome trace buffer under a per-request `tid` — each
request renders as its OWN row (`req[<id>]`, cat ``request``) next to the
steps and comm buckets that explain it, across every rank of a merged
dump.

Gating: fully inert under ``MXNET_TPU_TELEMETRY=0`` (and under
``MXNET_TPU_SERVE_TRACE=0``, the bench's A/B knob): `start()` returns the
no-op `NULL_TRACE`, the ring stays empty, no spans are recorded —
`tests/test_observability.py` asserts it from a subprocess.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque

__all__ = ["RequestTrace", "NullRequestTrace", "NULL_TRACE", "start",
           "records", "reset", "default_ring_size",
           "default_span_cap", "tracing_enabled"]


def default_ring_size():
    try:
        return max(8, int(os.environ.get("MXNET_TPU_SERVE_TRACE_RING",
                                         "128")))
    except (TypeError, ValueError):
        return 128


def default_span_cap():
    """Spans kept per trace; past the cap, marks coalesce into the last
    span (counted) so a 10k-token stream cannot balloon the ring."""
    try:
        return max(16, int(os.environ.get("MXNET_TPU_SERVE_TRACE_SPANS",
                                          "512")))
    except (TypeError, ValueError):
        return 512


def tracing_enabled():
    """Telemetry master switch AND the request-trace knob (the bench's
    overhead A/B lever)."""
    from .. import telemetry as _telem
    if not _telem.ENABLED:
        return False
    return os.environ.get("MXNET_TPU_SERVE_TRACE", "1").lower() not in (
        "0", "false", "off")


class NullRequestTrace:
    """The disabled-path trace: every method a no-op, so scheduler call
    sites never branch on the telemetry gate."""

    __slots__ = ()
    null = True

    def mark(self, name, **meta):
        return self

    def note_replica(self, name):
        return self

    def note_drain(self, error=None):
        return self

    def finish(self, outcome, **meta):
        return None

    def to_payload(self):
        return None


NULL_TRACE = NullRequestTrace()


class RequestTrace:
    """One request's span timeline on the shared telemetry span clock.

    A trace is owned by exactly one scheduler thread at a time (the
    replica that holds the stream — ownership transfers through the
    RequestQueue exactly like the stream itself), so `mark`/`note_*` are
    single-writer; the ring stores the immutable `to_payload()` snapshot
    taken at `finish()`, which is what concurrent scrapes read.
    """

    __slots__ = ("request_id", "trace_id", "rank", "t_enqueue",
                 "enqueued_unix", "spans", "replicas", "outcome",
                 "requeues", "_cursor", "_recovering", "_dropped_spans",
                 "_finished", "_cap", "_t_finish")
    null = False

    def __init__(self, request_id):
        from .. import telemetry as _telem
        self.request_id = str(request_id)
        self.trace_id = _telem.trace_id()
        self.rank = _telem.safe_rank()
        self.t_enqueue = _telem.span_clock()
        self.enqueued_unix = time.time()
        self.spans = []            # [name, start_s, dur_s, meta-dict]
        self.replicas = []         # replica names that held the stream
        self.outcome = None
        self.requeues = 0
        self._cursor = self.t_enqueue
        self._recovering = False
        self._dropped_spans = 0
        self._finished = False
        self._cap = default_span_cap()
        self._t_finish = None

    # ------------------------------------------------------------- marks
    def mark(self, name, **meta):
        """Close the open interval [cursor, now] as span `name` and
        advance the cursor — consecutive marks tile the timeline."""
        from .. import telemetry as _telem
        now = _telem.span_clock()
        dur = max(0.0, now - self._cursor)
        if self._recovering and name == "queue":
            # the wait after a drain is recovery time, not admission load
            name = "recovery.queue"
            self._recovering = False
        if len(self.spans) >= self._cap:
            # coalesce into the newest span (decode tails of huge streams)
            last = self.spans[-1]
            last[2] += dur
            last[3]["coalesced"] = last[3].get("coalesced", 0) + 1
            self._dropped_spans += 1
        else:
            self.spans.append([name, self._cursor, dur, dict(meta)])
        self._cursor = now
        return self

    def note_replica(self, name):
        """Record which replica holds the stream (admission time) — the
        cross-replica hop list a recovered request's post-mortem needs."""
        name = str(name)
        if not self.replicas or self.replicas[-1] != name:
            self.replicas.append(name)
        return self

    def note_drain(self, error=None):
        """A replica fault drained this stream: close the open interval as
        ``recovery.drain`` and flag the next queue wait as recovery."""
        self.mark("recovery.drain",
                  error=type(error).__name__ if error is not None else None)
        self._recovering = True
        self.requeues += 1
        return self

    # ------------------------------------------------------------ finish
    def finish(self, outcome, **meta):
        """Terminal event: close the tail, snapshot the payload into the
        ring, and replay the spans into the chrome buffer as this
        request's own row. Returns the payload (embedded by
        `DeadlineExceeded` and drain post-mortems). Idempotent."""
        from .. import telemetry as _telem
        if self._finished:
            return self.to_payload(**meta)
        self._finished = True
        self._t_finish = _telem.span_clock()
        self.outcome = str(outcome)
        payload = self.to_payload(**meta)
        if _telem.ENABLED:
            _record(payload)
            # chrome row per request: stable small tid from the id, spans
            # named req[<id>].<phase> so a merged multi-rank dump shows
            # the request's hops next to each rank's steps
            tid = zlib.crc32(self.request_id.encode()) & 0x3fffffff
            for name, start, dur, _meta in self.spans:
                _telem.record_span(
                    "req[%s].%s" % (self.request_id, name), "request",
                    start, dur, tid=tid)
        return payload

    # ----------------------------------------------------------- export
    def _phase_ms(self):
        out = {}
        for name, _start, dur, _meta in self.spans:
            key = name.split(".", 1)[0]  # recovery.* folds into recovery
            out[key] = out.get(key, 0.0) + dur * 1e3
        return {k: round(v, 3) for k, v in out.items()}

    def to_payload(self, **meta):
        """JSON-able snapshot: identity, outcome, per-phase rollup, and
        the span timeline (starts relative to enqueue, ms)."""
        # wall runs to the finish clock, NOT the last mark's cursor —
        # otherwise accounted == wall tautologically and the >=95% bound
        # could never catch a lost tail (last token -> deadline detection)
        end = self._t_finish if self._t_finish is not None else self._cursor
        wall_ms = (end - self.t_enqueue) * 1e3
        accounted_ms = sum(dur for _n, _s, dur, _m in self.spans) * 1e3
        payload = {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "rank": self.rank,
            "enqueued_unix": self.enqueued_unix,
            "outcome": self.outcome,
            "replicas": list(self.replicas),
            "requeues": self.requeues,
            "wall_ms": round(wall_ms, 3),
            "accounted_ms": round(accounted_ms, 3),
            "phases_ms": self._phase_ms(),
            "spans": [{"name": n, "start_ms": round((s - self.t_enqueue)
                                                    * 1e3, 3),
                       "dur_ms": round(d * 1e3, 3), **m}
                      for n, s, d, m in self.spans],
        }
        if self._dropped_spans:
            payload["coalesced_spans"] = self._dropped_spans
        payload.update(meta)
        return payload


# --------------------------------------------------------------- the ring
_RING = deque(maxlen=default_ring_size())
_RING_LOCK = threading.Lock()


def _record(payload):
    with _RING_LOCK:
        _RING.append(payload)


def start(request_id):
    """Factory the scheduler calls at enqueue: a live trace, or the
    NULL_TRACE no-op when telemetry / request tracing is off."""
    if not tracing_enabled():
        return NULL_TRACE
    return RequestTrace(request_id)


def records(limit=None):
    """Completed-request payloads, oldest first (the `/requests` body)."""
    with _RING_LOCK:
        out = list(_RING)
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def reset():
    global _RING
    with _RING_LOCK:
        _RING = deque(maxlen=default_ring_size())
