"""Comm-overlap attribution: per-step compute/collective/host/idle
decomposition from recorded spans.

The bucketed comm engine's overlap has so far been *asserted* — "bucket
N's collective overlaps bucket N+1's pack under async dispatch" — never
measured. This module turns the spans the runtime already records (one
``comm.bucket[...]`` / ``comm.key[...]`` / ``comm.rs[...]`` /
``comm.ag[...]`` span per launch, one cat-``step`` span per
trainer/fused/serve step) into the measured evidence ROADMAP item #4's
schedule autotuner consumes, with NO new instrumentation burden at comm
call sites.

The model — host-side attribution, stated honestly: every span here is a
**host** interval (the time the dispatching thread spent inside the
call); device execution is asynchronous underneath. For one step window
``[t0, t1]`` the decomposition is a *partition* (it sums to the step time
exactly, which is why the acceptance's 5 % bound holds by construction):

* ``collective_ms`` — time covered by comm-cat spans: the host was inside
  a collective dispatch/launch (the *exposed* comm cost — per-launch
  latency × launches; the thing bucketing shrinks);
* ``host_ms``       — time covered by host-overhead spans (resilience
  checkpoints/restores/backoff, injected faults, user profiler scopes)
  not already counted as comm;
* ``idle_ms``       — time covered by explicit cat-``idle`` spans (queue
  parks); zero where none are recorded;
* ``compute_ms``    — the remainder: the host was off the comm/overhead
  path — packing the next bucket, dispatching compute, or running python
  while previously-launched device work (including in-flight collectives)
  proceeds underneath.

``overlap_frac`` is the bucketed engine's overlap claim made measurable:
within the step's *comm phase* (first collective launch → step end — the
region where collectives are in flight), the fraction the host spent OFF
the collective path, i.e. free to overlap pack/compute against in-flight
comm. Per-parameter sync (``MXNET_TPU_COMM_BUCKET_MB=0``) serializes the
host through N launches and drives the fraction down; bucketing frees the
phase and drives it up — the 0-vs-default delta `BENCH=comm` reports.

Surfaces: `telemetry.overlap_report()` (full per-step report),
``parse_log --overlap`` (same table from a chrome trace dump, stdlib
re-derivation), per-step ``attrib`` records in the flight recorder, and
``attrib.<site>.*`` gauges for scrapers — all inert under
``MXNET_TPU_TELEMETRY=0`` because `step_event` (the only live caller)
already is.
"""
from __future__ import annotations

__all__ = ["COMM_CATS", "HOST_CATS", "IDLE_CATS", "STEP_CAT",
           "attribute_window", "overlap_report", "step_attribution",
           "interval_union"]

COMM_CATS = frozenset(("comm",))
HOST_CATS = frozenset(("host", "resilience", "fault", "user"))
IDLE_CATS = frozenset(("idle",))
STEP_CAT = "step"

# spans fed to the per-step live pass (step_event): bounded tail so the
# attribution of one step never pays O(ring) on the 100k-span buffer; a
# window that outruns it (per-param sync over >512 params) widens once to
# _TAIL_SPANS_MAX and past THAT is counted, never silently clipped
_TAIL_SPANS = 512
_TAIL_SPANS_MAX = 8192


def interval_union(intervals):
    """Merge [(start, end)] into disjoint intervals; returns (total
    covered duration, merged list)."""
    if not intervals:
        return 0.0, []
    intervals = sorted(intervals)
    merged = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1][1] = e
        else:
            merged.append([s, e])
    return sum(e - s for s, e in merged), [(s, e) for s, e in merged]


def _clip(events, cats, t0, t1):
    """[(start, end)] of spans in `cats` clipped to [t0, t1]."""
    out = []
    for name, cat, ts, dur, _tid in events:
        if cat not in cats:
            continue
        s, e = max(ts, t0), min(ts + dur, t1)
        if e > s:
            out.append((s, e))
    return out


def _subtract(intervals, cover):
    """`intervals` minus the (merged, disjoint) `cover` list."""
    out = []
    for s, e in intervals:
        cur = s
        for cs, ce in cover:
            if ce <= cur:
                continue
            if cs >= e:
                break
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def attribute_window(events, t0, t1):
    """Decompose one step window into the compute/collective/host/idle
    partition (ms) + comm-launch stats + overlap_frac. `events` are
    (name, cat, ts_s, dur_s, tid) tuples on the same clock as t0/t1."""
    width = max(0.0, t1 - t0)
    comm_iv = _clip(events, COMM_CATS, t0, t1)
    comm_busy = sum(e - s for s, e in comm_iv)
    collective, comm_cover = interval_union(comm_iv)
    host_raw = _clip(events, HOST_CATS, t0, t1)
    host, host_cover = interval_union(_subtract(host_raw, comm_cover))
    idle_raw = _subtract(_subtract(_clip(events, IDLE_CATS, t0, t1),
                                   comm_cover), host_cover)
    idle, _ = interval_union(idle_raw)
    compute = max(0.0, width - collective - host - idle)
    out = {
        "step_ms": round(width * 1e3, 3),
        "compute_ms": round(compute * 1e3, 3),
        "collective_ms": round(collective * 1e3, 3),
        "host_ms": round(host * 1e3, 3),
        "idle_ms": round(idle * 1e3, 3),
        "comm_launches": len(comm_iv),
        # dispatch concurrency across threads (busy > union means two
        # threads were inside collective launches at once)
        "comm_busy_ms": round(comm_busy * 1e3, 3),
    }
    if comm_iv:
        phase_start = min(s for s, _e in comm_iv)
        phase = t1 - phase_start
        in_phase, _ = interval_union(_clip(events, COMM_CATS,
                                           phase_start, t1))
        out["comm_phase_ms"] = round(phase * 1e3, 3)
        out["overlap_frac"] = round(
            max(0.0, phase - in_phase) / phase, 4) if phase > 0 else 0.0
    else:
        out["comm_phase_ms"] = 0.0
        out["overlap_frac"] = None
    return out


def _step_spans(events, site=None):
    return [(name, ts, dur) for name, cat, ts, dur, _tid in events
            if cat == STEP_CAT and (site is None or name == site)]


def overlap_report(events=None, site=None, limit=None):
    """Per-step attribution over every recorded cat-``step`` span (or
    just `site`'s). `events` defaults to the live span buffer; pass a
    trace dump's event list for post-hoc analysis. Returns::

        {"steps": [{"site", "ts_s", <attribute_window fields>}...],
         "summary": {"steps", "step_ms", "compute_ms", "collective_ms",
                     "host_ms", "idle_ms", "comm_launches",
                     "overlap_frac"}}   # sums; overlap_frac comm-phase-
                                        # weighted mean over comm steps

    The per-step partition sums to the step time exactly; the summary
    sums therefore do too.
    """
    if events is None:
        from .. import telemetry as _telem
        events = _telem.span_events()
    steps = _step_spans(events, site)
    if limit is not None and len(steps) > limit:
        steps = steps[-limit:]
    rows = []
    for name, ts, dur in steps:
        row = {"site": name, "ts_s": round(ts, 6)}
        row.update(attribute_window(events, ts, ts + dur))
        rows.append(row)
    summary = {"steps": len(rows), "overlap_frac": None}
    for key in ("step_ms", "compute_ms", "collective_ms", "host_ms",
                "idle_ms", "comm_launches", "comm_busy_ms"):
        summary[key] = round(sum(r[key] for r in rows), 3)
    phase_total = sum(r["comm_phase_ms"] for r in rows)
    if phase_total > 0:
        summary["overlap_frac"] = round(
            sum(r["overlap_frac"] * r["comm_phase_ms"] for r in rows
                if r["overlap_frac"] is not None) / phase_total, 4)
    return {"site": site, "steps": rows, "summary": summary}


def step_attribution(site, dur_ms, trace_buffer):
    """The live per-step pass `telemetry.step_event` runs: attribute the
    window that just ended ([now - dur, now] on the span clock — no step
    span lookup needed), publish ``attrib.<site>.*`` gauges, and return
    the compact record the flight recorder embeds. Returns None when the
    window saw no spans at all (nothing to attribute)."""
    from .. import telemetry as _telem
    t1 = trace_buffer.now()
    t0 = t1 - dur_ms / 1e3
    events = trace_buffer.tail(_TAIL_SPANS)
    if len(events) == _TAIL_SPANS and events[0][2] > t0:
        # the tail does not reach back to the step start — widen once
        # (flat per-param sync records one span per param), and count the
        # residual truncation instead of silently under-attributing
        events = trace_buffer.tail(_TAIL_SPANS_MAX)
        if len(events) == _TAIL_SPANS_MAX and events[0][2] > t0:
            _telem.inc("telemetry.attrib.window_truncated")
    # the step's own span (recorded just before step_event) must not
    # shadow the window; attribute_window already ignores cat "step"
    row = attribute_window(events, t0, t1)
    if not row["comm_launches"] and row["host_ms"] == 0.0 \
            and row["idle_ms"] == 0.0:
        return None
    for key in ("compute_ms", "collective_ms", "host_ms", "idle_ms"):
        _telem.set_gauge("attrib.%s.%s" % (site, key), row[key])
    if row["overlap_frac"] is not None:
        _telem.set_gauge("attrib.%s.overlap_frac" % site,
                         row["overlap_frac"])
    return {"compute_ms": row["compute_ms"],
            "collective_ms": row["collective_ms"],
            "host_ms": row["host_ms"], "idle_ms": row["idle_ms"],
            "comm_launches": row["comm_launches"],
            "overlap_frac": row["overlap_frac"]}
