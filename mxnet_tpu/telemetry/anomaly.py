"""Step-time anomaly detection: rolling-median spike + SLO tracking.

A fleet dashboard does not want every step time — it wants to know the
moment step 412 took 3× the steps around it (a retrace storm, a swapped-in
straggler host, a dying HBM) or blew through the serving SLO. This module
watches the per-site step cadence the instrumented train/serve paths
already measure and turns regressions into counters and trace markers the
rest of the observability plane (exporter, flight recorder, mxtop,
`parse_log --anomalies`) picks up for free:

* **spike** — a step exceeding ``k × rolling median`` of the last
  ``MXNET_TPU_ANOMALY_WINDOW`` (default 64) steps of the same site, after
  a short warm-up, increments ``telemetry.anomaly.step_time`` (+ per-site)
  and records a zero-duration ``anomaly@<site>`` marker span (cat
  ``anomaly``) so the spike is findable in a chrome trace next to the
  spans that explain it. ``MXNET_TPU_ANOMALY_FACTOR`` sets k (default 4).
* **SLO** — with ``MXNET_TPU_STEP_SLO_MS`` set, any step over the budget
  increments ``telemetry.anomaly.slo`` (+ per-site) — the serving-latency
  contract, landed ahead of the serving engine.

The same rolling windows answer the latency questions a scrape cannot
(histogram buckets are too coarse for tails): `quantiles(site)` returns
p50/p99 over the window, exported by the `/snapshot` endpoint, the JSONL
stream, and `bench.py` rows.

Everything here is behind the telemetry gate: callers route through
`telemetry.step_event`, which is a no-op when `MXNET_TPU_TELEMETRY=0`.
"""
from __future__ import annotations

import os
import threading
from collections import deque

__all__ = ["StepTimeTracker", "observe", "quantiles", "quantiles_all",
           "reset", "default_window", "default_factor", "default_slo_ms"]

# spikes only fire once the window has seen enough steps to trust a median
WARMUP_STEPS = 8


def default_window():
    try:
        return max(WARMUP_STEPS,
                   int(os.environ.get("MXNET_TPU_ANOMALY_WINDOW", "64")))
    except (TypeError, ValueError):
        return 64


def default_factor():
    try:
        return float(os.environ.get("MXNET_TPU_ANOMALY_FACTOR", "4"))
    except (TypeError, ValueError):
        return 4.0


def default_slo_ms():
    raw = os.environ.get("MXNET_TPU_STEP_SLO_MS")
    if not raw:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def _median(sorted_vals):
    n = len(sorted_vals)
    mid = n // 2
    if n % 2:
        return sorted_vals[mid]
    return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])


def _quantile(sorted_vals, q):
    """Nearest-rank quantile over an already-sorted list."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class StepTimeTracker:
    """Per-site rolling window of step durations with spike/SLO detection."""

    def __init__(self, window=None, factor=None, slo_ms=None):
        self.window = window or default_window()
        self.factor = factor if factor is not None else default_factor()
        self.slo_ms = slo_ms if slo_ms is not None else default_slo_ms()
        self._windows = {}  # site -> deque of recent step durations (ms)
        self._lock = threading.Lock()

    def observe(self, site, dur_ms):
        """Record one step; returns the list of anomaly kinds it fired
        (empty for a normal step). Telemetry counters/spans are emitted by
        the caller-facing module function so the tracker stays pure."""
        dur_ms = float(dur_ms)
        fired = []
        with self._lock:
            win = self._windows.get(site)
            if win is None:
                win = self._windows[site] = deque(maxlen=self.window)
            if len(win) >= WARMUP_STEPS:
                med = _median(sorted(win))
                if med > 0 and dur_ms > self.factor * med:
                    fired.append(("step_time", med))
            if self.slo_ms is not None and dur_ms > self.slo_ms:
                fired.append(("slo", self.slo_ms))
            # the spike joins the window AFTER the check (it must not vote
            # on its own median) — and then raises the baseline, so a
            # genuine regime change stops firing once it IS the new normal
            win.append(dur_ms)
        return fired

    def quantiles(self, site):
        """{"p50", "p99", "n", "last_ms"} over the site's rolling window,
        or None for an unseen site."""
        with self._lock:
            win = self._windows.get(site)
            if not win:
                return None
            vals = sorted(win)
            last = win[-1]
        return {"p50": _quantile(vals, 0.50), "p99": _quantile(vals, 0.99),
                "n": len(vals), "last_ms": last}

    def quantiles_all(self):
        with self._lock:
            sites = list(self._windows)
        out = {}
        for site in sites:
            q = self.quantiles(site)
            if q is not None:
                out[site] = q
        return out

    def reset(self):
        with self._lock:
            self._windows.clear()


_TRACKER = StepTimeTracker()


def observe(site, dur_ms):
    """Module-level entry point (called by `telemetry.step_event`): run the
    tracker and emit the `telemetry.anomaly.*` counters + marker span for
    whatever fired. Returns the fired kinds (for the flight recorder)."""
    from .. import telemetry as _telem
    fired = _TRACKER.observe(site, dur_ms)
    for kind, baseline in fired:
        _telem.inc("telemetry.anomaly.%s" % kind)
        _telem.inc("telemetry.anomaly.%s.%s" % (kind, site))
        # zero-duration marker next to the slow span it indicts
        _telem.record_span("anomaly@%s" % site, "anomaly",
                           _telem.span_clock(), 0.0)
    return [kind for kind, _ in fired]


def quantiles(site):
    return _TRACKER.quantiles(site)


def quantiles_all():
    return _TRACKER.quantiles_all()


def reset():
    """Drop all rolling windows AND re-read the env knobs (tests monkeypatch
    MXNET_TPU_STEP_SLO_MS / _FACTOR / _WINDOW around a reset)."""
    global _TRACKER
    _TRACKER = StepTimeTracker()
