"""Cross-worker telemetry aggregation (fleet view).

`mx.telemetry` is per-process; a multi-host mesh has one registry per
worker, and a fleet dashboard wants ONE view: total retries, total bytes
pushed, the worst stall. This module merges counter/gauge/histogram
snapshots across all workers of the multi-controller runtime.

Mechanism: each worker serializes its snapshot to JSON bytes, the buffers
are length-padded and exchanged with one
``multihost_utils.process_allgather`` (riding the same DCN collectives as
training — no side channel, no extra server), and every worker merges the
decoded snapshots identically:

* counters — summed (call counts, bytes, faults are extensive),
* gauges — ``value``/``max`` take the max across workers (they are
  watermarks; a fleet watermark is the worst offender),
* histograms — bucket-wise sum, count/sum summed, min/max of the extremes,
* plus a ``workers`` key: how many snapshots were merged.

Key sets may differ per worker (e.g. only rank 0 ran a compile) — the
merge is over the union. Single-process: returns the local snapshot merged
with nothing, same shape, so dashboards need no special case.

Both entry points here are COLLECTIVE (lockstep) on multi-worker
runtimes. The scrape-driven sibling is `telemetry.federation`: rank 0's
``/fleet/*`` endpoints collect every peer's ``/snapshot`` out-of-band
over HTTP and run the SAME `merge_snapshots` — one fleet view a
Prometheus scraper can pull at any moment, no barrier required.
"""
from __future__ import annotations

import json

__all__ = ["merge_snapshots", "aggregate_snapshot", "aggregate_trace"]


def _merge_hist(a, b):
    buckets = dict(a.get("buckets", {}))
    for k, n in b.get("buckets", {}).items():
        buckets[k] = buckets.get(k, 0) + n
    count = a.get("count", 0) + b.get("count", 0)
    total = a.get("sum", 0.0) + b.get("sum", 0.0)
    mins = [v for v in (a.get("min"), b.get("min")) if v is not None]
    maxs = [v for v in (a.get("max"), b.get("max")) if v is not None]
    return {"count": count, "sum": total,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "avg": (total / count) if count else None,
            # same-named histograms share bounds across ranks; keep them
            # so quantile estimation stays exact-edged post-merge
            "bounds": a.get("bounds") or b.get("bounds"),
            "buckets": buckets}


def merge_snapshots(snaps):
    """Merge a list of `Registry.snapshot()` dicts into one fleet view."""
    out = {"counters": {}, "gauges": {}, "histograms": {},
           "workers": len(snaps)}
    for snap in snaps:
        for name, v in snap.get("counters", {}).items():
            out["counters"][name] = out["counters"].get(name, 0) + v
        for name, g in snap.get("gauges", {}).items():
            cur = out["gauges"].get(name)
            if cur is None:
                out["gauges"][name] = {"value": g["value"], "max": g["max"]}
            else:
                cur["value"] = max(cur["value"], g["value"])
                cur["max"] = max(cur["max"], g["max"])
        for name, h in snap.get("histograms", {}).items():
            cur = out["histograms"].get(name)
            out["histograms"][name] = _merge_hist(cur, h) if cur else \
                _merge_hist(h, {})
    return out


def _exchange_json(payload_bytes):
    """All-gather variable-length byte strings across workers: gather the
    lengths (fixed shape), right-pad to the global max, gather the padded
    buffers, trim. One extra scalar collective is the price of not forcing
    every worker to have identical metric sets."""
    import numpy as _np
    from jax.experimental import multihost_utils
    local = _np.frombuffer(payload_bytes, dtype=_np.uint8)
    lengths = multihost_utils.process_allgather(
        _np.asarray([local.size], _np.int32))
    lengths = _np.asarray(lengths).reshape(-1)
    width = int(lengths.max())
    padded = _np.zeros((width,), _np.uint8)
    padded[:local.size] = local
    stacked = _np.asarray(multihost_utils.process_allgather(padded))
    stacked = stacked.reshape(-1, width)
    return [stacked[i, :int(n)].tobytes() for i, n in enumerate(lengths)]


def aggregate_snapshot(snapshot=None):
    """Fleet-wide merged snapshot (every worker returns the same dict).

    Collective: on a multi-worker runtime EVERY process must call this at
    the same point (like a barrier). Single-process calls are local-only
    and always safe.
    """
    from .. import telemetry as _telem
    from ..parallel import dist
    if snapshot is None:
        snapshot = _telem.snapshot()
    if dist.num_workers() <= 1:
        return merge_snapshots([snapshot])
    blobs = _exchange_json(
        json.dumps(snapshot, sort_keys=True).encode("utf-8"))
    return merge_snapshots([json.loads(b.decode("utf-8")) for b in blobs])


def aggregate_trace(dump=None):
    """Fleet-wide span exchange: every worker's recorded span events (plus
    its rank, trace id, and the wall-clock anchor of its span epoch) over
    the same length-padded allgather `aggregate_snapshot` rides. Returns
    `[{rank, trace_id, epoch_unix, events}]` sorted by rank — the input
    shape of `trace.write_merged_chrome_trace`.

    The run-wide trace id is unified here: every worker adopts rank 0's,
    so a merged dump (and every later per-rank dump) names ONE run.

    Collective on multi-worker runtimes — call in lockstep, like
    `aggregate_snapshot`. Single-process: returns the local dump only.
    """
    from .. import telemetry as _telem
    from ..parallel import dist
    if dump is None:
        dump = _telem.local_trace_dump()
    if dist.num_workers() <= 1:
        return [dump]
    blobs = _exchange_json(json.dumps(dump).encode("utf-8"))
    dumps = sorted((json.loads(b.decode("utf-8")) for b in blobs),
                   key=lambda d: int(d.get("rank", 0)))
    run_id = dumps[0].get("trace_id")
    if run_id:
        _telem.set_trace_id(run_id)
        for d in dumps:
            d["trace_id"] = run_id
    return dumps
