"""Runtime telemetry: counters, gauges, histograms, chrome-trace spans.

The observability spine of the framework (ROADMAP: every perf/robustness PR
reports through it). Instrumented hot paths:

* `gluon.CachedOp` — `cachedop.cache_hit` / `cachedop.cache_miss` /
  `cachedop.compile` / `cachedop.retrace` counters plus a
  `cachedop.compile_ms` histogram and one span per (re)trace, so silent
  recompiles become visible;
* `nd.invoke` — `ndarray.invoke` dispatch counter, and the forced
  device→host syncs `ndarray.sync.asnumpy` / `ndarray.sync.wait_to_read`
  (the classic hidden stall under async PjRt dispatch);
* `kvstore` — `kvstore.push_calls` / `pull_calls` and payload
  `push_bytes` / `pull_bytes`;
* bucketed comm engine (`mx.engine`) — `comm.collectives` (launched comm
  programs: per bucket when bucketing, per key on the escape hatch),
  `comm.bucket.count` / `comm.bucket.bytes` /
  `comm.bucket.flush_reason.{full,dtype_split,oversize,final}` /
  `comm.bucket.skipped`, plus one `comm.bucket[k0..kN]` span per launch
  (cat `comm`) so overlap is visible in chrome-trace dumps;
* dataloader — `dataloader.batchify.syncs_saved` (device→host syncs
  avoided by the batched collate);
* train steps — `trainer.step_ms`, `fused_step.step_ms`,
  `train_step.step_ms` histograms + compile counters;
* memory — best-effort `memory.*.bytes_in_use` watermark gauges from the
  PjRt allocator (memory.py).

Gating: `MXNET_TPU_TELEMETRY=0` (env) or `telemetry.disable()` turns every
instrumented path into a single global-bool check — no locks, no dict
lookups, no allocation. Default is enabled (counters are cheap; spans are
bounded by a ring buffer).

Export: `snapshot()` (dict), `dumps(format='table'|'json')`,
`dump(path)` (JSON), and `dump_trace(path)` — a chrome://tracing-loadable
host-side trace, the analog of the reference's `Profiler::DumpProfile`.
`mx.profiler.dumps()` also embeds the counter snapshot, so the existing
profiler API surfaces telemetry.

Telemetry v2 — the LIVE observability plane on top of the registry:

* `telemetry.export` — a Prometheus `/metrics`+`/snapshot` HTTP endpoint
  (`MXNET_TPU_METRICS_PORT`) and a periodic JSONL snapshot streamer
  (`MXNET_TPU_METRICS_STREAM`), both off by default and fully inert when
  telemetry is disabled; `tools/mxtop.py` is the matching dashboard;
* cross-rank correlation — every chrome-trace dump is stamped with this
  worker's rank and a run-wide `trace_id()`; `aggregate_trace()` exchanges
  span events fleet-wide and `dump_trace(merged=True)` writes ONE trace
  with a process row per rank on a shared clock;
* `telemetry.flight` — a crash flight recorder: bounded ring of per-step
  records (step ms, comm deltas, compiles/retrace reasons, anomalies,
  resilience events), embedded in watchdog post-mortems and auto-dumped
  on fatal resilience errors / unhandled exceptions;
* `telemetry.anomaly` — rolling-median step-time spike + SLO detection
  (`telemetry.anomaly.*` counters, `anomaly@<site>` marker spans) and the
  rolling p50/p99 step-latency quantiles the exporter and bench rows
  report. `step_event(site, ms)` is the one call the instrumented step
  paths make to feed both.

Observability v3 — the per-request / per-step / per-fleet evidence layer:

* `telemetry.request_trace` — a `RequestTrace` travels with every
  `mx.serve` request (enqueue → admit → prefill → each decode step →
  completion/shed/recovery), its spans tiling the request's wall clock;
  completed traces land in a bounded ring (`/requests` endpoint,
  `request_traces()`, `parse_log --requests`) and replay into the chrome
  dump as one row per request;
* `telemetry.attribution` — per-step compute/collective/host/idle
  decomposition + comm overlap fraction from the spans the runtime
  already records (`overlap_report()`, `parse_log --overlap`,
  per-step `attrib` flight records, `attrib.<site>.*` gauges) — the
  measured-evidence input of ROADMAP item #4's schedule autotuner;
* `telemetry.federation` — rank 0's exporter proxies the WHOLE fleet
  (`/fleet/metrics`, `/fleet/snapshot`): out-of-band per-peer scrapes
  merged with the same host-side merge `aggregate_snapshot` uses,
  stale-rank tolerant (`telemetry.federation.stale_ranks`).
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from contextlib import contextmanager

from .metrics import Counter, Gauge, Histogram, Registry
from .trace import (TraceBuffer, write_chrome_trace,
                    write_merged_chrome_trace)
from . import memory as _memory

__all__ = ["enabled", "enable", "disable", "registry", "counter", "gauge",
           "histogram", "inc", "set_gauge", "observe", "span", "record_span",
           "snapshot", "compile_report", "reset", "dumps", "dump",
           "dump_trace", "span_events",
           "aggregate_snapshot", "merge_snapshots", "aggregate_trace",
           "sample_memory", "maybe_sample_memory",
           "note_compile", "recent_compiles", "device_report",
           "trace_id", "set_trace_id", "safe_rank", "local_trace_dump",
           "step_event", "step_quantiles", "flight_records",
           "request_traces", "overlap_report",
           "memory_scopes", "memory_programs", "capture_profile",
           "Counter", "Gauge", "Histogram", "Registry"]

# the ONLY state instrumented code reads on the disabled fast path
ENABLED = os.environ.get("MXNET_TPU_TELEMETRY", "1").lower() not in (
    "0", "false", "off")

registry = Registry()
_trace = TraceBuffer()


def enabled():
    return ENABLED


def enable():
    """Turn telemetry on at runtime. Also (re-)checks the live-export env
    knobs: a process that started under MXNET_TPU_TELEMETRY=0 with
    MXNET_TPU_METRICS_PORT set gets its endpoint the moment telemetry is
    switched on, not never."""
    global ENABLED
    ENABLED = True
    from . import export as _export
    _export.maybe_start_from_env()


def disable():
    global ENABLED
    ENABLED = False


# ---------------------------------------------------------------- metrics API
def counter(name):
    return registry.counter(name)


def gauge(name):
    return registry.gauge(name)


def histogram(name, bounds=None):
    return registry.histogram(name, bounds)


def inc(name, n=1):
    """Increment a counter; no-op (and no metric created) when disabled."""
    if not ENABLED:
        return 0
    return registry.counter(name).inc(n)


def set_gauge(name, value):
    if not ENABLED:
        return
    registry.gauge(name).set(value)


def observe(name, value):
    if not ENABLED:
        return
    registry.histogram(name).observe(value)


# ---------------------------------------------------------------- span API
class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "_t0")

    def __init__(self, name, cat):
        self.name = name
        self.cat = cat
        self._t0 = None

    def __enter__(self):
        self._t0 = _trace.now()
        return self

    def __exit__(self, *exc):
        _trace.add(self.name, self.cat, self._t0, _trace.now() - self._t0)
        return False


def span(name, cat="host"):
    """Context manager recording one chrome-trace span (ph:'X')."""
    if not ENABLED:
        return _NULL_SPAN
    return _Span(name, cat)


def record_span(name, cat, start_s, dur_s, tid=None):
    """Record an already-timed range. start_s is on the buffer's own
    perf_counter epoch — pair with `span_clock()`. `tid` overrides the
    chrome row (default: the recording thread) — per-request trace rows
    use it."""
    if not ENABLED:
        return
    _trace.add(name, cat, start_s, dur_s, tid=tid)


def span_clock():
    """Current timestamp on the trace buffer's epoch (seconds)."""
    return _trace.now()


def span_events(limit=None):
    """Recorded spans as (name, cat, ts_s, dur_s, tid) tuples, oldest first;
    `limit` keeps only the newest N. The resilience watchdog embeds this
    tail in `StallError` so a hang post-mortem starts with data."""
    events = _trace.events()
    if limit is not None and len(events) > limit:
        events = events[-limit:]
    return events


# ---------------------------------------------------------------- identity
# the run-wide trace id every span dump / flight dump / stream line carries.
# MXNET_TPU_TRACE_ID pins it fleet-wide from the launcher; otherwise each
# process draws its own and `aggregate_trace()` unifies on rank 0's at the
# first collective exchange.
_RUN_LOCK = threading.Lock()
_RUN = {"trace_id": os.environ.get("MXNET_TPU_TRACE_ID") or None}


def trace_id():
    """The run-wide trace id (lazily drawn; stable for the process life)."""
    with _RUN_LOCK:
        if _RUN["trace_id"] is None:
            _RUN["trace_id"] = uuid.uuid4().hex[:16]
        return _RUN["trace_id"]


def set_trace_id(value):
    """Adopt a trace id (rank 0's, via `aggregate_trace`; or an external
    orchestrator's)."""
    with _RUN_LOCK:
        _RUN["trace_id"] = str(value)


def safe_rank():
    """This worker's rank WITHOUT triggering backend init: the dist state
    when rendezvoused, the launcher env otherwise. (dist.rank() falls back
    to jax.process_index(), which would initialize the platform — too heavy
    for a metrics scrape or an import-time exporter.)"""
    try:
        from ..parallel.dist import _STATE
        if _STATE.get("initialized"):
            return int(_STATE["rank"])
    except Exception:  # noqa: BLE001 - identity is best-effort
        pass
    try:
        return int(os.environ.get("DMLC_WORKER_ID", "0") or 0)
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------- compiles
# ring of the most recent compiled executables (name, epoch-relative ts) —
# a stall post-mortem wants "what did we last hand the device", not just a
# compile *count*. Bounded; guarded by its own lock (the compile paths run
# on whatever thread dispatched).
_COMPILE_RING_LIMIT = 32
_compiles = []
_compiles_lock = threading.Lock()


def note_compile(name):
    """Record that executable `name` was just (re)compiled — called by
    CachedOp / FusedTrainStep / ShardedTrainStep next to their `*.compile`
    counters; surfaces in `recent_compiles()` and stall post-mortems."""
    if not ENABLED:
        return
    ts = _trace.now()
    with _compiles_lock:
        _compiles.append((str(name), ts))
        if len(_compiles) > _COMPILE_RING_LIMIT:
            del _compiles[:-_COMPILE_RING_LIMIT]


def recent_compiles(limit=None):
    """The newest compiled executables as (name, ts_s) tuples, oldest
    first."""
    with _compiles_lock:
        events = list(_compiles)
    if limit is not None and len(events) > limit:
        events = events[-limit:]
    return events


# ---------------------------------------------------------------- memory
def device_report():
    """Best-effort per-device PjRt state (allocator stats + live-buffer
    attribution) for post-mortems — see telemetry.memory.device_report."""
    return _memory.device_report()


def sample_memory():
    """Force one device-memory gauge sample; returns #devices reporting."""
    if not ENABLED:
        return 0
    return _memory.sample(registry)


def maybe_sample_memory():
    """Rate-limited sample for per-step call sites."""
    if not ENABLED:
        return 0
    return _memory.maybe_sample(registry)


def memory_scopes():
    """The HBM ledger's {scope: bytes} snapshot (params / optimizer /
    grad_buckets / kv pools / programs / unattributed — see
    telemetry/ledger.py); {} when the ledger is disabled."""
    from . import ledger as _ledger
    return _ledger.scopes()


def memory_programs():
    """Recorded per-executable static footprints
    (`compiled.memory_analysis()` harvested at compile/AOT-restore time);
    [] when the ledger is disabled."""
    from . import ledger as _ledger
    return _ledger.programs()


def capture_profile(ms=None, dir=None):     # noqa: A002 - knob name
    """Capture one on-demand profiling window (rate-limited; see
    telemetry/profiling.py). Returns the trace path or None."""
    from . import profiling as _profiling
    return _profiling.capture_profile(ms=ms, dir=dir)


# ---------------------------------------------------------------- export
def snapshot():
    return registry.snapshot()


def compile_report():
    """Metric snapshot + the recent-compiles ring as ONE json-able dict —
    the input `tools/parse_log.py --compile` tabulates (compiler/cache
    counters, lower/compile latency, fallbacks by reason, and WHICH
    executables were built, tagged [cached] vs fresh)."""
    report = snapshot()
    report["recent_compiles"] = [[name, round(ts, 6)]
                                 for name, ts in recent_compiles()]
    return report


def reset():
    """Drop all metrics, recorded spans, the compile ring, the flight
    recorder, the request-trace ring, the anomaly windows, the memory
    ledger, and the profiling state (does not change ENABLED)."""
    registry.reset()
    _trace.clear()
    with _compiles_lock:
        del _compiles[:]
    from . import anomaly as _anomaly, flight as _flight
    from . import ledger as _ledger, profiling as _profiling
    from . import request_trace as _reqtrace
    _anomaly.reset()
    _flight.reset()
    _reqtrace.reset()
    _ledger.reset()
    _profiling.reset()


def dumps(format="table"):
    return registry.dumps(format=format)


def dump(path, format="json"):
    """Write the metric snapshot to `path` (json/table)."""
    with open(path, "w") as f:
        f.write(registry.dumps(format=format))
    return path


def dump_trace(path=None, merged=False):
    """Write recorded spans + counters as chrome://tracing JSON, stamped
    with this worker's rank and the run trace id. Default path:
    telemetry_trace.json in the cwd. Returns the path.

    merged=True exchanges span events fleet-wide first (`aggregate_trace`
    — collective: every worker must call it in lockstep) and writes ONE
    trace with a process row per rank on a shared wall-clock base, so
    cross-rank overlap (comm buckets vs compute) is visible in a single
    chrome://tracing load. Single-process merged dumps are local-only."""
    if path is None:
        path = "telemetry_trace.json"
    if merged:
        dumps_by_rank = aggregate_trace()
        write_merged_chrome_trace(path, dumps_by_rank, registry,
                                  local_rank=safe_rank())
    else:
        write_chrome_trace(path, _trace, registry, rank=safe_rank(),
                           trace_id=trace_id())
    return path


def local_trace_dump():
    """This worker's span events + identity — the per-rank unit
    `aggregate_trace` exchanges."""
    return {"rank": safe_rank(), "trace_id": trace_id(),
            "epoch_unix": _trace.epoch_unix,
            "events": [list(e) for e in _trace.events()]}


def aggregate_trace(dump=None):
    """Fleet-wide span-event exchange (collective — lockstep like
    `aggregate_snapshot`); returns `[{rank, trace_id, epoch_unix, events}]`
    sorted by rank. See telemetry/aggregate.py."""
    from .aggregate import aggregate_trace as _agg
    return _agg(dump)


# ---------------------------------------------------------------- step plane
def step_event(site, dur_ms, info=None):
    """One call per training/serving step from the instrumented step paths
    (`trainer` / `fused_step` / `train_step` / `serve.step`): runs anomaly
    detection over the duration, attributes the step window
    (compute/collective/host/idle + overlap — telemetry.attribution), and
    appends a flight-recorder record with this step's counter deltas.
    `info` (a small JSON-able dict — e.g. the serving scheduler's
    active/completed request ids) rides into the flight record verbatim.
    No-op when disabled."""
    if not ENABLED:
        return
    from . import anomaly as _anomaly, attribution as _attrib
    from . import flight as _flight, ledger as _ledger
    fired = _anomaly.observe(site, dur_ms)
    extras = dict(info) if info else {}
    attrib = _attrib.step_attribution(site, dur_ms, _trace)
    if attrib is not None:
        extras["attrib"] = attrib
    _flight.record_step(site, dur_ms, anomalies=fired,
                        extras=extras or None)
    # per-step ledger reconcile (rate-limited inside): the unattributed
    # residual tracks the run, not just its post-mortem
    _ledger.maybe_reconcile()


def step_quantiles(site=None):
    """Rolling p50/p99 step-latency quantiles: one site's dict, or
    {site: dict} for all sites when `site` is None."""
    from . import anomaly as _anomaly
    if site is not None:
        return _anomaly.quantiles(site)
    return _anomaly.quantiles_all()


def flight_records(limit=None):
    """The flight recorder's step records, oldest first (see
    telemetry/flight.py); the watchdog embeds the tail in `StallError`."""
    from . import flight as _flight
    return _flight.records(limit=limit)


def request_traces(limit=None):
    """Completed per-request trace payloads, oldest first — the last-N
    ring `mx.serve` feeds and the `/requests` endpoint serves (see
    telemetry/request_trace.py)."""
    from . import request_trace as _reqtrace
    return _reqtrace.records(limit=limit)


def overlap_report(events=None, site=None, limit=None):
    """Per-step compute/collective/host/idle decomposition + comm overlap
    fraction from recorded spans (see telemetry/attribution.py) — the
    measured evidence the comm-schedule autotuner consumes and
    `parse_log --overlap` tabulates."""
    from . import attribution as _attrib
    return _attrib.overlap_report(events=events, site=site, limit=limit)


def aggregate_snapshot(snapshot=None):
    """Fleet-wide snapshot: this worker's (or `snapshot`) merged with every
    other worker's over one DCN allgather — counters sum, gauge watermarks
    take the fleet max, histograms merge bucket-wise. Collective on
    multi-worker runtimes; local-only (and cheap) on one process. See
    telemetry/aggregate.py."""
    from .aggregate import aggregate_snapshot as _agg
    return _agg(snapshot)


def merge_snapshots(snaps):
    """Pure merge of snapshot dicts (the host-side half of
    `aggregate_snapshot`) — usable on dumps collected out-of-band."""
    from .aggregate import merge_snapshots as _merge
    return _merge(snaps)


# ------------------------------------------------------------- live export
# start whatever live transports the env configures (MXNET_TPU_METRICS_PORT
# endpoint / MXNET_TPU_METRICS_STREAM JSONL). Both default OFF; when
# telemetry is disabled this is a pure no-op — no thread, no port — which
# tests assert. Import order matters: `export` reads this module's ENABLED
# and registry, both defined above.
from . import export  # noqa: E402  (needs ENABLED/registry above)

export.maybe_start_from_env()
