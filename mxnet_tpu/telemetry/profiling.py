"""On-demand device profiling: a rate-limited capture window.

Deep profiles (XLA op timelines, HBM traffic) are too heavy to run
always-on, and the moment an operator *wants* one — a replica suddenly
slow, a step time regressing — is mid-incident, when restarting the
process with a profiler attached is exactly what nobody can afford. This
module captures a bounded `jax.profiler.trace` window **on demand** in
the live process:

* `capture_profile(ms=N)` — programmatic trigger;
* ``GET /profile?ms=N`` on the telemetry endpoint (`telemetry.export`) —
  the operator trigger (`tools/mxtop.py`'s ``p`` key hits it);
* every capture is announced in the flight ring (``profile`` event), so
  a post-mortem names the trace files that cover the crash window.

On an accelerator backend the window is a real `jax.profiler.trace`
(TensorBoard-loadable). On CPU — where the device profiler is mostly
noise — the fallback writes the telemetry span buffer as a
chrome://tracing JSON covering the window instead, so the endpoint
answers with *something* on every backend.

Rate limiting is the safety contract: at most one capture per
``MXNET_TPU_PROFILE_MIN_S`` (default 30) and never two concurrently —
a scrape loop (or a stuck retry button) cannot turn the profiler into
a denial of service. Throttled calls return None and count
``profile.rate_limited``.

Knobs: ``MXNET_TPU_PROFILE_DIR`` (capture directory; default a
``mxnet_tpu_profiles`` dir under the system tmp — never the workspace),
``MXNET_TPU_PROFILE_MS`` (default window 500 ms, clamped to [10, 60000]),
``MXNET_TPU_PROFILE_MIN_S`` (rate limit). Fully inert under
``MXNET_TPU_TELEMETRY=0``: no directory, no file, no capture.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time

__all__ = ["capture_profile", "records", "default_profile_dir",
           "default_window_ms", "min_interval_s", "reset"]

_MAX_WINDOW_MS = 60000
_RECORD_LIMIT = 16

_lock = threading.Lock()
_state = {"last_ts": 0.0, "active": False}
_records = []           # newest last: {ts, path, kind, ms}


def _telem():
    from .. import telemetry
    return telemetry


def default_profile_dir():
    return (os.environ.get("MXNET_TPU_PROFILE_DIR")
            or os.path.join(tempfile.gettempdir(), "mxnet_tpu_profiles"))


def default_window_ms():
    try:
        return max(10, min(_MAX_WINDOW_MS, int(
            os.environ.get("MXNET_TPU_PROFILE_MS", "500"))))
    except (TypeError, ValueError):
        return 500


def min_interval_s():
    try:
        return max(0.0, float(os.environ.get("MXNET_TPU_PROFILE_MIN_S",
                                             "30")))
    except (TypeError, ValueError):
        return 30.0


def _on_accelerator():
    try:
        import jax
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False


def _record(path, kind, ms):
    entry = {"ts": time.time(), "path": path, "kind": kind, "ms": int(ms)}
    with _lock:
        _records.append(entry)
        if len(_records) > _RECORD_LIMIT:
            del _records[:-_RECORD_LIMIT]
    return entry


def capture_profile(ms=None, dir=None):     # noqa: A002 - knob name
    """Capture one profiling window; returns the trace path, or None when
    disabled, throttled, already capturing, or the capture failed (every
    outcome is counted — the caller never gets an exception out of a
    diagnostic)."""
    telem = _telem()
    if not telem.ENABLED:
        return None
    interval = min_interval_s()
    now = time.monotonic()
    with _lock:
        if _state["active"] or (now - _state["last_ts"] < interval
                                and _state["last_ts"] > 0.0):
            throttled = True
        else:
            throttled = False
            _state["active"] = True
            _state["last_ts"] = now
    if throttled:
        telem.inc("profile.rate_limited")
        return None
    try:
        window_ms = default_window_ms() if ms is None else \
            max(10, min(_MAX_WINDOW_MS, int(ms)))
        out_dir = dir or default_profile_dir()
        stamp = "%d_%d" % (int(time.time()), os.getpid())
        if _on_accelerator():
            path = os.path.join(out_dir, "device_%s" % stamp)
            try:
                import jax
                os.makedirs(path, exist_ok=True)
                with jax.profiler.trace(path):
                    time.sleep(window_ms / 1e3)
                kind = "device"
            except Exception:
                telem.inc("profile.errors")
                return None
        else:
            # CPU fallback: the host-side span window as a chrome trace —
            # the profiler story this backend actually has
            path = os.path.join(out_dir, "spans_%s.json" % stamp)
            try:
                os.makedirs(out_dir, exist_ok=True)
                time.sleep(window_ms / 1e3)
                telem.dump_trace(path)
                kind = "cpu_spans"
            except Exception:
                telem.inc("profile.errors")
                return None
        telem.inc("profile.captures")
        _record(path, kind, window_ms)
        from . import flight
        flight.note_event("profile", "%s (%s, %dms)"
                          % (path, kind, window_ms))
        return path
    finally:
        with _lock:
            _state["active"] = False


def records(limit=None):
    """Recent capture records (ts/path/kind/ms dicts), oldest first."""
    with _lock:
        out = [dict(r) for r in _records]
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def reset():
    """Forget captures and the rate-limit clock (tests re-arm the
    throttle this way)."""
    with _lock:
        _state["last_ts"] = 0.0
        _state["active"] = False
        del _records[:]
