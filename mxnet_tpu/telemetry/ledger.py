"""HBM memory ledger: every byte attributed to the subsystem holding it.

The watermark sampler (`telemetry.memory`) answers "how much HBM is in
use"; pod-scale runs die on the question it cannot answer — "*whose*
bytes are they?". The ledger is a registry of named memory **scopes**,
fed by explicit `account()` calls at the allocation sites that already
exist:

======================  ====================================================
scope                   accounted by
======================  ====================================================
``params``              `ShardedTrainStep.init()` / `place()` (re-layout)
``optimizer``           `ZeroUpdater` state gauge / train-step opt state
``grad_buckets``        `engine.BucketLayout` (frozen flat-gradient layout)
``kv_pool``             `serve.KVBlockPool` storage (target model)
``kv_draft``            the draft model's mirrored pool     [spec decoding]
``prefix_cache``        prefix-index pinned blocks (OVERLAY: these bytes
                        live inside ``kv_pool`` storage and are excluded
                        from the reconcile sum)
``programs``            per-executable static footprints from
                        ``compiled.memory_analysis()`` (temp + generated
                        code), harvested at every compile/AOT-restore site
``unattributed``        the reconcile residual (see below)
======================  ====================================================

Per-program **static footprints** are harvested wherever an executable is
built or restored (`compiler/cache.load_or_compile`, the whole-graph
`GraphProgram.compiled`, serve warm-up, the sharded train step's AOT
path) via `harvest()` + `note_program()`. The footprint is stored INSIDE
the AOT cache entry's meta, so a warm restore reports the same numbers
without recompiling — the fleet cold-start path stays observable.

`reconcile()` compares the scoped total against the device's own story
(`Device.memory_stats()` where the backend has an allocator; the
`jax.live_arrays()` byte total as the CPU fallback): the residual is the
``unattributed`` scope — a growing residual means an allocation site the
ledger does not know about. `maybe_reconcile()` rate-limits to one probe
per `MIN_RECONCILE_S` so `step_event` can call it unconditionally.

Every scope exports a ``memory.scope.<name>.bytes`` gauge (→ `/metrics`,
`/snapshot`, the JSONL stream); `format_scopes()` renders the top-scopes
breakdown that OOM / `Overloaded(kv_exhausted)` / `StallError`
post-mortems embed; `check_budget()` validates a run against a declared
per-chip budget (the SCALE.md acceptance seam for ROADMAP item #3).

Gating: inert under ``MXNET_TPU_TELEMETRY=0`` (no state, no gauges) and
under ``MXNET_TPU_LEDGER=0`` (the bench A/B lever — telemetry stays up,
the ledger alone goes quiet).
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["account", "adjust", "scopes", "programs", "note_program",
           "harvest", "reconcile", "maybe_reconcile", "last_reconcile",
           "check_budget", "tree_nbytes", "format_scopes", "breakdown",
           "enabled", "reset", "SCOPES", "OVERLAY_SCOPES",
           "MIN_RECONCILE_S"]

# the canonical scope names (account() accepts others — a future subsystem
# should not need a ledger edit to be accountable)
SCOPES = ("params", "optimizer", "grad_buckets", "kv_pool", "kv_draft",
          "prefix_cache", "embedding", "programs", "unattributed")

# overlay scopes annotate bytes that ALREADY belong to another scope's
# allocation (prefix-cache blocks live inside kv_pool storage); they are
# reported but excluded from the reconcile sum, else sharing would be
# double-counted as allocation
OVERLAY_SCOPES = frozenset({"prefix_cache"})

MIN_RECONCILE_S = 1.0
_PROGRAM_LIMIT = 64     # newest-wins bound on the per-program table

_lock = threading.Lock()
_scopes = {}            # scope name -> bytes (absolute, set-semantics)
_programs = {}          # label -> footprint dict
_last = {"reconcile": None, "ts": 0.0}


def _telem():
    from .. import telemetry
    return telemetry


def enabled():
    """The ledger's own gate: telemetry on AND MXNET_TPU_LEDGER not off."""
    if not _telem().ENABLED:
        return False
    return os.environ.get("MXNET_TPU_LEDGER", "1").lower() not in (
        "0", "false", "off")


def _gauge(scope, nbytes):
    _telem().registry.gauge("memory.scope.%s.bytes" % scope).set(int(nbytes))


# ------------------------------------------------------------------ account
def account(scope, nbytes):
    """Set scope `scope`'s byte total (absolute — allocation sites know
    their own totals; there is no delta bookkeeping to drift). No-op when
    the ledger is disabled."""
    if not enabled():
        return
    nbytes = int(nbytes)
    with _lock:
        _scopes[str(scope)] = nbytes
    _gauge(scope, nbytes)


def adjust(scope, delta):
    """Add `delta` bytes to a scope (for sites that only know increments).
    Returns the new total, or None when disabled."""
    if not enabled():
        return None
    with _lock:
        total = _scopes.get(str(scope), 0) + int(delta)
        _scopes[str(scope)] = total
    _gauge(scope, total)
    return total


def scopes():
    """{scope: bytes} snapshot (includes overlay scopes and the last
    reconcile's ``unattributed`` residual); {} when disabled."""
    with _lock:
        return dict(_scopes)


def _scoped_total_locked():
    return sum(v for k, v in _scopes.items()
               if k not in OVERLAY_SCOPES and k != "unattributed")


# ----------------------------------------------------------------- programs
def harvest(compiled):
    """Best-effort static footprint of a `jax.stages.Compiled`:
    `memory_analysis()` sizes as a plain dict, or None when the backend
    does not expose them. Never raises — a footprint is evidence, not a
    dependency."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in (("temp_bytes", "temp_size_in_bytes"),
                      ("argument_bytes", "argument_size_in_bytes"),
                      ("output_bytes", "output_size_in_bytes"),
                      ("alias_bytes", "alias_size_in_bytes"),
                      ("code_bytes", "generated_code_size_in_bytes")):
        try:
            val = getattr(ma, attr, None)
        except Exception:
            val = None
        if val is not None:
            out[key] = int(val)
    if not out:
        return None
    # the bytes the program itself pins beyond its operands: XLA scratch +
    # generated code (arguments/outputs are the caller's arrays, already
    # accounted under their owning scopes)
    out["bytes"] = out.get("temp_bytes", 0) + out.get("code_bytes", 0)
    return out


def note_program(label, footprint, cached=False):
    """Record one executable's static footprint (newest wins per label) and
    refresh the ``programs`` scope = Σ(temp + generated code). `cached`
    marks an AOT-cache restore replaying the footprint stored at compile
    time. Tolerates footprint=None (backend without memory_analysis)."""
    if not enabled() or not footprint:
        return
    entry = dict(footprint)
    entry["label"] = str(label)
    entry["cached"] = bool(cached)
    with _lock:
        _programs[str(label)] = entry
        if len(_programs) > _PROGRAM_LIMIT:
            # drop the oldest insertion (dicts preserve order)
            _programs.pop(next(iter(_programs)))
        total = sum(p.get("bytes", 0) for p in _programs.values())
        _scopes["programs"] = total
    _gauge("programs", total)
    _telem().inc("ledger.programs.%s" % ("cached" if cached else "fresh"))


def programs():
    """Recorded per-program footprints, oldest first (list of dicts with
    label/cached/bytes/temp_bytes/...); [] when disabled."""
    with _lock:
        return [dict(p) for p in _programs.values()]


# ---------------------------------------------------------------- reconcile
def _device_bytes():
    """(total bytes, source, device count) from the backend: allocator
    stats where the platform has them, the live-array byte total as the
    CPU fallback, (0, "none", 0) when jax is absent."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return 0, "none", 0
    total = 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        in_use = (stats or {}).get("bytes_in_use")
        if in_use is not None:
            total += int(in_use)
            reported += 1
    if reported:
        return total, "memory_stats", len(devices)
    # CPU (or a backend without allocator stats): the live-array walk is
    # the only byte total available
    total = 0
    try:
        for arr in jax.live_arrays():
            nbytes = getattr(arr, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
    except Exception:
        return 0, "none", len(devices)
    return total, "live_arrays", len(devices)


def reconcile():
    """Compare the scoped total against the device's own byte count; the
    residual becomes the ``unattributed`` scope (gauged). Returns the
    reconcile dict ``{device_bytes, scoped_bytes, residual_bytes, source,
    device_count, ts}`` or None when disabled."""
    if not enabled():
        return None
    device_total, source, n_dev = _device_bytes()
    with _lock:
        scoped = _scoped_total_locked()
        residual = device_total - scoped if source != "none" else 0
        _scopes["unattributed"] = residual
        report = {
            "device_bytes": device_total,
            "scoped_bytes": scoped,
            "residual_bytes": residual,
            "source": source,
            "device_count": n_dev,
            "ts": time.time(),
        }
        _last["reconcile"] = report
        _last["ts"] = time.monotonic()
    _gauge("unattributed", residual)
    return dict(report)


def maybe_reconcile():
    """Rate-limited reconcile for per-step call sites (`step_event`)."""
    if not enabled():
        return None
    with _lock:
        due = time.monotonic() - _last["ts"] >= MIN_RECONCILE_S
    if not due:
        return None
    return reconcile()


def last_reconcile():
    """The most recent reconcile dict (None before the first)."""
    with _lock:
        report = _last["reconcile"]
    return dict(report) if report else None


# ------------------------------------------------------------------- budget
def check_budget(budget_bytes_per_chip, residual_tolerance=0.25):
    """Validate the run against a declared per-chip HBM budget (the
    SCALE.md acceptance seam): reconciles, then checks that (a) the
    per-chip device total fits the budget and (b) the per-scope breakdown
    sums to within ``residual_tolerance`` (a fraction of the device
    total) — i.e. the ledger actually explains the memory it budgets.

    Returns ``{ok, budget_bytes_per_chip, per_chip_bytes, device_bytes,
    scoped_bytes, residual_bytes, residual_frac, device_count, source,
    scopes, failures}``; never raises. ``ok`` is False when disabled
    (an unaccountable run cannot pass a budget check)."""
    report = reconcile()
    if report is None:
        return {"ok": False, "failures": ["ledger disabled"],
                "budget_bytes_per_chip": int(budget_bytes_per_chip),
                "scopes": {}}
    n_dev = max(1, report["device_count"])
    per_chip = report["device_bytes"] / n_dev
    denom = max(1, report["device_bytes"])
    residual_frac = abs(report["residual_bytes"]) / denom
    failures = []
    if report["source"] == "none":
        failures.append("no device byte source (jax unavailable)")
    if per_chip > int(budget_bytes_per_chip):
        failures.append(
            "per-chip bytes %d exceed budget %d"
            % (per_chip, int(budget_bytes_per_chip)))
    if residual_frac > float(residual_tolerance):
        failures.append(
            "unattributed residual %.1f%% of device total exceeds "
            "tolerance %.1f%%"
            % (residual_frac * 100, float(residual_tolerance) * 100))
    out = dict(report)
    out.update({
        "ok": not failures,
        "budget_bytes_per_chip": int(budget_bytes_per_chip),
        "per_chip_bytes": int(per_chip),
        "residual_frac": residual_frac,
        "scopes": scopes(),
        "failures": failures,
    })
    return out


# ---------------------------------------------------------------- rendering
def tree_nbytes(tree):
    """Total bytes of a pytree's array leaves (best-effort; 0 on failure
    — an accounting helper must never break the path it measures)."""
    try:
        import jax
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
        return total
    except Exception:
        return 0


def _fmt_bytes(n):
    n = float(n)
    sign = "-" if n < 0 else ""
    n = abs(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return "%s%.1f%s" % (sign, n, unit) if unit != "B" \
                else "%s%d%s" % (sign, int(n), unit)
        n /= 1024.0
    return "%s%.1fGiB" % (sign, n)


def breakdown(top=4):
    """One-line top-scopes summary for error messages:
    ``kv_pool=1.5GiB, params=1.2GiB, ... (scoped 3.1GiB)``. Empty string
    when the ledger is disabled or has nothing."""
    snap = scopes()
    ranked = sorted(((k, v) for k, v in snap.items()
                     if k != "unattributed" and v), key=lambda kv: -kv[1])
    if not ranked:
        return ""
    parts = ["%s=%s" % (k, _fmt_bytes(v)) for k, v in ranked[:top]]
    total = sum(v for k, v in snap.items()
                if k not in OVERLAY_SCOPES and k != "unattributed")
    return "%s (scoped %s)" % (", ".join(parts), _fmt_bytes(total))


def format_scopes():
    """Multi-line scope table for post-mortems (`StallError.format_report`
    embeds it): one line per scope, largest first, overlay scopes and the
    residual annotated."""
    snap = scopes()
    if not snap:
        return "memory ledger: empty"
    lines = ["memory ledger (per-scope bytes):"]
    for name, val in sorted(snap.items(), key=lambda kv: -abs(kv[1])):
        tag = ""
        if name in OVERLAY_SCOPES:
            tag = "  [overlay]"
        elif name == "unattributed":
            tag = "  [residual]"
        lines.append("  %-14s %12d  (%s)%s"
                     % (name, val, _fmt_bytes(val), tag))
    return "\n".join(lines)


def reset():
    """Drop every scope, program footprint, and reconcile record (does not
    change the enable gates)."""
    with _lock:
        _scopes.clear()
        _programs.clear()
        _last["reconcile"] = None
        _last["ts"] = 0.0
