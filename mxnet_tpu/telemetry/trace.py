"""Host-side span recording + chrome://tracing export.

The analog of the reference's `Profiler::DumpProfile`
(src/profiler/profiler.cc), which serializes recorded ranges to the chrome
trace-event JSON format. Here spans are recorded host-side into a bounded
ring buffer (the device timeline belongs to `jax.profiler`'s XPlane dump;
these spans cover what XLA cannot see: trace/compile time, step cadence,
kvstore calls, forced syncs) and exported as complete-duration ("ph": "X")
trace events, counters appended as chrome counter ("ph": "C") samples.

Load the dump at chrome://tracing or https://ui.perfetto.dev.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["TraceBuffer", "write_chrome_trace", "write_merged_chrome_trace"]

MAX_EVENTS = 100000


class TraceBuffer:
    """Bounded ring of (name, cat, ts_s, dur_s, tid) span records."""

    def __init__(self, maxlen=MAX_EVENTS):
        self._events = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        # one session epoch so ts stays small and monotonic across threads;
        # the wall-clock stamp of the SAME instant anchors this rank's spans
        # on the fleet-shared clock (merged multi-rank dumps shift each
        # rank's events by its epoch offset)
        self._epoch = time.perf_counter()
        self.epoch_unix = time.time()

    def now(self):
        return time.perf_counter() - self._epoch

    def add(self, name, cat, ts_s, dur_s, tid=None):
        """Append one span. `tid` defaults to the recording thread's ident
        (chrome renders one row per tid); callers with their own row
        semantics — per-request trace rows — pass an explicit id."""
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            self._events.append((name, cat, ts_s, dur_s, tid))

    def events(self):
        with self._lock:
            return list(self._events)

    def tail(self, n):
        """The newest `n` spans (oldest first) without copying the whole
        ring — the per-step attribution pass runs on every step_event and
        must not pay O(ring) on a 100k-event buffer."""
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            it = reversed(self._events)
            out = [next(it) for _ in range(n)]
        out.reverse()
        return out

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        return len(self._events)


def write_chrome_trace(path, buffer, registry=None, process_name="mxnet_tpu",
                       rank=0, trace_id=None):
    """Serialize the span buffer (+ current counter values) to a
    chrome://tracing-loadable JSON file; returns the event count.

    Every event is stamped with this worker's rank (as the chrome `pid`,
    so each rank renders as its own process row) and the run-wide trace id
    travels in the payload metadata — a dump from any rank names the run
    it belongs to, and N per-rank dumps are mergeable after the fact."""
    rank = int(rank or 0)
    events = [{"name": "process_name", "ph": "M", "pid": rank, "tid": 0,
               "args": {"name": "%s rank %d" % (process_name, rank)}}]
    last_ts = 0.0
    for name, cat, ts_s, dur_s, tid in buffer.events():
        ts_us = ts_s * 1e6
        events.append({"name": name, "cat": cat, "ph": "X",
                       "ts": ts_us, "dur": dur_s * 1e6,
                       "pid": rank, "tid": tid})
        last_ts = max(last_ts, ts_us)
    if registry is not None:
        counters = registry.snapshot()["counters"]
        for name, value in counters.items():
            events.append({"name": name, "cat": "counter", "ph": "C",
                           "ts": last_ts, "pid": rank,
                           "args": {"value": value}})
    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "metadata": {"rank": rank, "trace_id": trace_id,
                            "epoch_unix": buffer.epoch_unix}}
    with open(path, "w") as f:
        json.dump(payload, f)
    return len(events)


def write_merged_chrome_trace(path, rank_dumps, registry=None,
                              process_name="mxnet_tpu", local_rank=0):
    """Serialize per-rank trace dumps (`[{rank, epoch_unix, trace_id,
    events}]`, the `aggregate_trace()` shape) into ONE chrome trace: one
    process row per rank, every rank's spans shifted onto a shared clock.

    Each rank's span timestamps are on its own perf_counter epoch; the
    wall-clock stamp of that epoch (`epoch_unix`) re-bases them all onto
    the earliest rank's epoch, so cross-rank overlap (e.g. the comm-bucket
    collectives of a lock-stepped fleet) lines up to wall-clock skew, not
    to nothing. Returns the event count."""
    rank_dumps = sorted(rank_dumps, key=lambda d: int(d.get("rank", 0)))
    if not rank_dumps:
        raise ValueError("write_merged_chrome_trace: no rank dumps")
    # clock base over the dumps that carry an anchor; a dump WITHOUT one
    # (out-of-band, pre-v2) merges unshifted instead of throwing every
    # anchored rank ~epoch-seconds off the timeline
    anchors = [float(d["epoch_unix"]) for d in rank_dumps
               if d.get("epoch_unix") is not None]
    base = min(anchors) if anchors else 0.0
    trace_id = rank_dumps[0].get("trace_id")
    events = []
    local_last_ts = {}
    for dump in rank_dumps:
        rank = int(dump.get("rank", 0))
        epoch = dump.get("epoch_unix")
        shift_s = (float(epoch) - base) if epoch is not None else 0.0
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0,
                       "args": {"name": "%s rank %d" % (process_name, rank)}})
        for name, cat, ts_s, dur_s, tid in dump.get("events", ()):
            ts_us = (ts_s + shift_s) * 1e6
            events.append({"name": name, "cat": cat, "ph": "X",
                           "ts": ts_us, "dur": dur_s * 1e6,
                           "pid": rank, "tid": tid,
                           "args": {"rank": rank}})
            local_last_ts[rank] = max(local_last_ts.get(rank, 0.0), ts_us)
    if registry is not None:
        # counters are per-process state: attach the LOCAL registry's values
        # to the local rank's row (each rank's merged dump carries its own)
        local_rank = int(local_rank or 0)
        ts = local_last_ts.get(local_rank, 0.0)
        for name, value in registry.snapshot()["counters"].items():
            events.append({"name": name, "cat": "counter", "ph": "C",
                           "ts": ts, "pid": local_rank,
                           "args": {"value": value}})
    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "metadata": {"trace_id": trace_id, "merged": True,
                            "ranks": [int(d.get("rank", 0))
                                      for d in rank_dumps]}}
    with open(path, "w") as f:
        json.dump(payload, f)
    return len(events)
