"""Host-side span recording + chrome://tracing export.

The analog of the reference's `Profiler::DumpProfile`
(src/profiler/profiler.cc), which serializes recorded ranges to the chrome
trace-event JSON format. Here spans are recorded host-side into a bounded
ring buffer (the device timeline belongs to `jax.profiler`'s XPlane dump;
these spans cover what XLA cannot see: trace/compile time, step cadence,
kvstore calls, forced syncs) and exported as complete-duration ("ph": "X")
trace events, counters appended as chrome counter ("ph": "C") samples.

Load the dump at chrome://tracing or https://ui.perfetto.dev.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["TraceBuffer", "write_chrome_trace"]

MAX_EVENTS = 100000


class TraceBuffer:
    """Bounded ring of (name, cat, ts_s, dur_s, tid) span records."""

    def __init__(self, maxlen=MAX_EVENTS):
        self._events = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        # one session epoch so ts stays small and monotonic across threads
        self._epoch = time.perf_counter()

    def now(self):
        return time.perf_counter() - self._epoch

    def add(self, name, cat, ts_s, dur_s):
        with self._lock:
            self._events.append(
                (name, cat, ts_s, dur_s, threading.get_ident()))

    def events(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        return len(self._events)


def write_chrome_trace(path, buffer, registry=None, process_name="mxnet_tpu"):
    """Serialize the span buffer (+ current counter values) to a
    chrome://tracing-loadable JSON file; returns the event count."""
    events = [{"name": process_name, "ph": "M", "pid": 0, "tid": 0,
               "args": {"name": process_name}}]
    last_ts = 0.0
    for name, cat, ts_s, dur_s, tid in buffer.events():
        ts_us = ts_s * 1e6
        events.append({"name": name, "cat": cat, "ph": "X",
                       "ts": ts_us, "dur": dur_s * 1e6,
                       "pid": 0, "tid": tid})
        last_ts = max(last_ts, ts_us)
    if registry is not None:
        counters = registry.snapshot()["counters"]
        for name, value in counters.items():
            events.append({"name": name, "cat": "counter", "ph": "C",
                           "ts": last_ts, "pid": 0,
                           "args": {"value": value}})
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return len(events)
