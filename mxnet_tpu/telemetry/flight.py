"""Crash flight recorder: a bounded ring of per-step context that survives
to the post-mortem.

Telemetry counters say *how much*; a crash dump needs *what just happened*.
The flight recorder keeps the last ``MXNET_TPU_FLIGHT_STEPS`` (default 256)
step records — step duration, comm volume and collectives launched this
step, compiles/retraces (with the guard's retrace reasons), the device
memory watermark, anomaly flags, and any resilience events (checkpoints,
restores, preemption notices) that landed since the previous step — fed by
the instrumented step paths (Trainer / FusedTrainStep / ShardedTrainStep
via `telemetry.step_event`).

The ring surfaces exactly when a run dies, which is when the process is
least able to ask for it:

* **StallError** — the watchdog embeds the ring tail in the error
  (`StallError.flight_dump`, rendered by ``format_report()``), so a hung
  collective's post-mortem opens with the last N steps of context;
* **fatal ResilienceError** — `ResilientRunner` dumps the ring to a JSON
  file before re-raising a fault it cannot recover from;
* **unhandled exception** — a chained ``sys.excepthook`` (installed
  lazily on the first record; ``MXNET_TPU_FLIGHT_AUTODUMP=0`` disables)
  writes the ring before the interpreter dies, and
  ``MXNET_TPU_FLIGHT_DUMP_AT_EXIT=1`` additionally dumps on every exit
  (ops fleets that collect artifacts unconditionally).

Dumps land in ``MXNET_TPU_FLIGHT_DIR`` (default: the runner's checkpoint
dir when it has one, else the system temp dir — never the workspace) as
``flight_rank<r>_<pid>.json`` and are tabulated by
``tools/parse_log.py --flight``. Everything is inert under
``MXNET_TPU_TELEMETRY=0``: no records, no hooks, no files.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "record_step", "note_event", "note_retrace",
           "records", "dump", "dump_on_crash", "format_records", "reset",
           "default_ring_steps"]

# counters whose per-step DELTA tells the step's story; absent counters are
# skipped, zero deltas are dropped from the record to keep the ring small
_DELTA_COUNTERS = (
    "comm.collectives", "comm.bucket.count", "comm.bucket.bytes",
    "kvstore.push_bytes", "kvstore.pull_bytes",
    "cachedop.compile", "fused_step.compile", "train_step.compile",
    "cachedop.retrace", "fused_step.retrace", "train_step.retrace",
    "ndarray.sync.asnumpy",
    "ops.pallas.dispatch", "ops.pallas.fallback",
    "resilience.retries", "resilience.restores", "resilience.stalls",
    "resilience.checkpoints", "resilience.faults_injected",
    "resilience.preempt.notices",
)

_REASON_LIMIT = 8     # retrace reasons buffered between two step records
_EVENT_LIMIT = 16     # resilience events buffered between two step records


def default_ring_steps():
    try:
        return max(8, int(os.environ.get("MXNET_TPU_FLIGHT_STEPS", "256")))
    except (TypeError, ValueError):
        return 256


class FlightRecorder:
    """Bounded ring of per-step records plus the between-step event inbox."""

    def __init__(self, maxlen=None):
        self._ring = deque(maxlen=maxlen or default_ring_steps())
        self._lock = threading.Lock()
        self._seq = 0
        self._last_counters = {}
        self._last_compile_ts = -1.0
        self._reasons = []   # (site, reason) since the last record
        self._events = []    # (kind, detail, t) since the last record
        # resolved memory.<device>.bytes_in_use gauge, cached once found —
        # scanning the registry's name list every step would put an
        # O(n log n) sorted scan in the hot path; re-probe only every
        # _MEM_PROBE_EVERY records while unresolved (CPU backends may
        # never grow the gauge)
        self._mem_gauge = None
        self._mem_probe_in = 0

    _MEM_PROBE_EVERY = 32

    def _memory_gauge(self, _telem):
        """Cached lookup of device 0's bytes_in_use gauge (call under
        self._lock)."""
        if self._mem_gauge is None:
            if self._mem_probe_in > 0:
                self._mem_probe_in -= 1
                return None
            self._mem_probe_in = self._MEM_PROBE_EVERY
            for name in _telem.registry.names():
                if name.startswith("memory.") and \
                        name.endswith(".bytes_in_use"):
                    self._mem_gauge = _telem.registry.get(name)
                    break
        return self._mem_gauge

    # ------------------------------------------------------------------
    def record_step(self, site, dur_ms, anomalies=None, extras=None):
        """Append one step record; deltas are computed against the previous
        record, so the ring reads as a per-step ledger. `extras` is a
        small caller-supplied dict merged into the record verbatim — the
        serving scheduler passes the active/completed request ids per
        step (so a stall post-mortem names the in-flight requests, not
        just counters) and step_event passes the per-step
        compute/collective/host/idle attribution."""
        from .. import telemetry as _telem
        if not _telem.ENABLED:
            return None
        record = {
            "t": time.time(),
            "site": site,
            "step_ms": round(float(dur_ms), 3),
        }
        if anomalies:
            record["anomalies"] = list(anomalies)
        if extras:
            for key, value in extras.items():
                record.setdefault(str(key), value)
        with self._lock:
            # counters and the compile ring are snapshotted UNDER the
            # recorder lock: two step sites recording concurrently must
            # not interleave a read batch with another's _last_counters
            # update (that interleaving writes negative deltas into the
            # ring)
            snap_counters = {}
            for name in _DELTA_COUNTERS:
                metric = _telem.registry.get(name)
                if metric is not None:
                    snap_counters[name] = metric.value
            recent = _telem.recent_compiles()
            mem = self._memory_gauge(_telem)
            if mem is not None:
                record["mem_bytes_in_use"] = mem.value
            self._seq += 1
            record["seq"] = self._seq
            deltas = {}
            for name, value in snap_counters.items():
                d = value - self._last_counters.get(name, 0)
                if d:
                    deltas[name] = d
            self._last_counters.update(snap_counters)
            if deltas:
                record["deltas"] = deltas
            # the compile watermark is read AND advanced under the lock:
            # two step sites recording concurrently must not both claim
            # the same executables against a stale watermark
            compiles = [(n, ts) for n, ts in recent
                        if ts > self._last_compile_ts]
            if compiles:
                record["compiles"] = [n for n, _ in compiles]
                self._last_compile_ts = max(ts for _, ts in compiles)
            if self._reasons:
                record["retrace_reasons"] = [
                    "%s: %s" % (s, r) for s, r in self._reasons]
                del self._reasons[:]
            if self._events:
                record["events"] = ["%s %s" % (k, d)
                                    for k, d, _ in self._events]
                del self._events[:]
            self._ring.append(record)
        _maybe_install_crash_hook()
        return record

    def note_retrace(self, site, reason):
        """Buffer a retrace reason (from `analysis.guard.on_retrace`) for
        the next step record."""
        with self._lock:
            if len(self._reasons) < _REASON_LIMIT:
                self._reasons.append((str(site), str(reason or "unknown")))

    def note_event(self, kind, detail=""):
        """Buffer a resilience/runtime event (checkpoint, restore, preempt
        notice, ...) for the next step record."""
        with self._lock:
            if len(self._events) < _EVENT_LIMIT:
                self._events.append((str(kind), str(detail), time.time()))

    # ------------------------------------------------------------------
    def records(self, limit=None):
        with self._lock:
            out = list(self._ring)
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._last_counters.clear()
            self._last_compile_ts = -1.0
            del self._reasons[:]
            del self._events[:]
            self._seq = 0
            self._mem_gauge = None
            self._mem_probe_in = 0

    # ------------------------------------------------------------------
    def dump(self, path=None, reason=None, dir_hint=None):
        """Write the ring (+ identity: rank, trace id) as JSON; returns the
        path, or None when there is nothing to say. Destination precedence:
        explicit `path` > MXNET_TPU_FLIGHT_DIR > `dir_hint` (the runner
        passes its checkpoint dir — post-mortems land next to the state
        they explain) > the system temp dir (never the workspace: auto
        dumps must not litter a repo checkout)."""
        import tempfile
        from .. import telemetry as _telem
        recs = self.records()
        if not recs:
            return None
        rank = _telem.safe_rank()
        if path is None:
            path = os.path.join(
                os.environ.get("MXNET_TPU_FLIGHT_DIR") or dir_hint
                or tempfile.gettempdir(),
                "flight_rank%d_%d.json" % (rank, os.getpid()))
        payload = {
            "rank": rank,
            "trace_id": _telem.trace_id(),
            "dumped_at": time.time(),
            "reason": reason,
            "records": recs,
        }
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


def format_records(recs, limit=10):
    """Render step records as the post-mortem table `format_report` embeds
    (newest last)."""
    if not recs:
        return "flight recorder: empty"
    lines = ["flight recorder (last %d of %d steps):"
             % (min(limit, len(recs)), len(recs))]
    for r in recs[-limit:]:
        parts = ["  #%-6d %-12s %8.2f ms" % (r.get("seq", 0),
                                             r.get("site", "?"),
                                             r.get("step_ms", 0.0))]
        deltas = r.get("deltas", {})
        for key, label in (("comm.collectives", "coll"),
                           ("comm.bucket.bytes", "comm_B"),
                           ("resilience.restores", "restores")):
            if key in deltas:
                parts.append("%s=%s" % (label, deltas[key]))
        compiles = r.get("compiles")
        if compiles:
            parts.append("compiled=%s" % ",".join(compiles))
        if r.get("retrace_reasons"):
            parts.append("retrace=%s" % "; ".join(r["retrace_reasons"]))
        if r.get("anomalies"):
            parts.append("ANOMALY=%s" % ",".join(r["anomalies"]))
        if r.get("events"):
            parts.append("events=[%s]" % "; ".join(r["events"]))
        if r.get("active_requests"):
            # the serving post-mortem headline: WHICH requests were in
            # flight when the step stalled, not just how many
            parts.append("active=[%s]" % ",".join(r["active_requests"]))
        if r.get("completed_requests"):
            parts.append("done=[%s]" % ",".join(r["completed_requests"]))
        lines.append(" ".join(parts))
    return "\n".join(lines)


# ---------------------------------------------------------------- module API
_RECORDER = FlightRecorder()
_HOOK_LOCK = threading.Lock()
_HOOK = {"installed": False, "prev": None}


def record_step(site, dur_ms, anomalies=None, extras=None):
    return _RECORDER.record_step(site, dur_ms, anomalies=anomalies,
                                 extras=extras)


def note_event(kind, detail=""):
    from .. import telemetry as _telem
    if not _telem.ENABLED:
        return
    _RECORDER.note_event(kind, detail)


def note_retrace(site, reason):
    from .. import telemetry as _telem
    if not _telem.ENABLED:
        return
    _RECORDER.note_retrace(site, reason)


def records(limit=None):
    return _RECORDER.records(limit=limit)


def dump(path=None, reason=None, dir_hint=None):
    return _RECORDER.dump(path=path, reason=reason, dir_hint=dir_hint)


def dump_on_crash(reason, dir_hint=None):
    """Best-effort crash dump (fatal-resilience and excepthook path): never
    raises, returns the path or None."""
    try:
        return _RECORDER.dump(reason=reason, dir_hint=dir_hint)
    except Exception:  # noqa: BLE001 — a post-mortem must not mask the crash
        return None


def reset():
    _RECORDER.reset()


# ------------------------------------------------------------- crash hooks
def _autodump_enabled():
    return os.environ.get("MXNET_TPU_FLIGHT_AUTODUMP", "1").lower() not in (
        "0", "false", "off")


def _crash_excepthook(etype, value, tb):
    path = None
    if _autodump_enabled() and not issubclass(etype, KeyboardInterrupt):
        path = dump_on_crash("unhandled %s: %s" % (etype.__name__, value))
    if path:
        print("mxnet_tpu: flight recorder dumped to %s" % path,
              file=sys.stderr)
    prev = _HOOK["prev"] or sys.__excepthook__
    prev(etype, value, tb)


def _exit_dump():
    if os.environ.get("MXNET_TPU_FLIGHT_DUMP_AT_EXIT", "").lower() in (
            "1", "true", "on"):
        dump_on_crash("atexit")


def _maybe_install_crash_hook():
    """Install the excepthook chain + atexit dump once, lazily, only after
    the ring actually holds something worth dumping."""
    if _HOOK["installed"]:
        return
    with _HOOK_LOCK:
        if _HOOK["installed"]:
            return
        if not _autodump_enabled():
            _HOOK["installed"] = True  # explicit opt-out: never re-check
            return
        import atexit
        _HOOK["prev"] = sys.excepthook
        sys.excepthook = _crash_excepthook
        atexit.register(_exit_dump)
        _HOOK["installed"] = True
