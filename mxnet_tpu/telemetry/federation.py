"""Fleet scrape federation: ONE endpoint proxying every rank's telemetry.

`aggregate_snapshot()`/`aggregate_trace()` give the fleet view over DCN
collectives — correct, but **lockstep**: every rank must call them at the
same point, which a Prometheus scraper cannot arrange. Observing a pod
today therefore means scraping N ports. This module closes the carried
ROADMAP follow-on ("one endpoint proxying the fleet via
aggregate_snapshot"): rank 0's exporter grows ``/fleet/metrics`` and
``/fleet/snapshot``, which fan out OUT-OF-BAND — plain HTTP to each
peer's existing ``/snapshot`` endpoint — and merge with
`aggregate.merge_snapshots`, the very host-side half `aggregate_snapshot`
runs after its collective exchange. Same merge semantics, no lockstep, no
interference with training/serving collectives.

* ``/fleet/metrics``  — every rank's Prometheus series in one scrape,
  rank-labeled (the label the per-rank exporter already stamps), HELP/
  TYPE headers deduplicated so the blob stays parseable;
* ``/fleet/snapshot`` — ``{ranks: {r: payload}, merged: <fleet-summed
  snapshot>, stale_ranks, workers, ...}`` — what ``mxtop --serve
  --url .../fleet/snapshot`` renders.

Peers come from ``MXNET_TPU_FLEET_PEERS`` (comma-separated ``host:port``
of the OTHER ranks' exporters; the launcher knows every rank's metrics
port) or `configure([...])`. **Stale-rank tolerance**: a peer that fails
the ``MXNET_TPU_FLEET_TIMEOUT_S`` (default 2 s) fetch is served from its
last good payload, marked ``stale: true``, and counted under
``telemetry.federation.stale_ranks`` — one dead host must not blind the
fleet view. A peer that never answered is listed in ``missing``.

Fully inert under ``MXNET_TPU_TELEMETRY=0``: the endpoints live on the
exporter's HTTP server, which never starts disabled, and `fleet_snapshot`
itself answers None without touching the network.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

__all__ = ["configure", "peers", "fleet_snapshot", "fleet_metrics_text",
           "default_timeout_s", "reset"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

# peer override (configure()) + per-peer last-good payload cache; scrapes
# run on the exporter's handler threads, so every access takes the lock
_STATE = {"peers": None}
_CACHE = {}
_LOCK = threading.Lock()


def default_timeout_s():
    try:
        return max(0.1, float(os.environ.get("MXNET_TPU_FLEET_TIMEOUT_S",
                                             "2")))
    except (TypeError, ValueError):
        return 2.0


def _normalize(peer):
    peer = peer.strip()
    if not peer:
        return None
    if "://" not in peer:
        peer = "http://" + peer
    return peer.rstrip("/")


def configure(peer_list):
    """Set the peer exporters programmatically (rank 0's launcher/test
    hook); None returns control to MXNET_TPU_FLEET_PEERS."""
    with _LOCK:
        if peer_list is None:
            _STATE["peers"] = None
        else:
            _STATE["peers"] = [p for p in (_normalize(p)
                                           for p in peer_list) if p]
        _CACHE.clear()


def peers():
    """Effective peer URL list (without the /snapshot suffix)."""
    with _LOCK:
        if _STATE["peers"] is not None:
            return list(_STATE["peers"])
    raw = os.environ.get("MXNET_TPU_FLEET_PEERS", "")
    return [p for p in (_normalize(p) for p in raw.split(",")) if p]


def reset():
    configure(None)


def _fetch(url, timeout):
    with urllib.request.urlopen(url + "/snapshot", timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _fetch_all(urls, timeout):
    """[(url, payload-or-None)] in `urls` order, fetched concurrently: a
    scrape pays ~one timeout regardless of how many peers are down, not
    len(peers) x timeout serially on the exporter's handler thread."""
    if not urls:
        return []
    if len(urls) == 1:
        urls0 = urls[0]
        try:
            return [(urls0, _fetch(urls0, timeout))]
        except Exception:  # noqa: BLE001 — tolerated, handled by caller
            return [(urls0, None)]
    with ThreadPoolExecutor(max_workers=min(8, len(urls))) as pool:
        def one(url):
            try:
                return url, _fetch(url, timeout)
            except Exception:  # noqa: BLE001 — tolerated stale/dead peer
                return url, None
        return list(pool.map(one, urls))


def _insert_rank(by_rank, rank, payload):
    """Self-reported ranks can collide (serving replicas launched without
    distributed init all report 0): bump to the next free slot rather
    than silently dropping a peer from the fleet view — the payload
    itself still carries the rank it claimed."""
    while rank in by_rank:
        rank += 1
    by_rank[rank] = payload


def fleet_snapshot():
    """The fleet view, out-of-band: local payload + every peer's
    ``/snapshot``, merged. None when telemetry is disabled."""
    from .. import telemetry as _telem
    from . import export as _export
    from .aggregate import merge_snapshots
    if not _telem.ENABLED:
        return None
    timeout = default_timeout_s()
    resolved = []
    stale, missing = [], []
    for url, payload in _fetch_all(peers(), timeout):
        if payload is not None:
            with _LOCK:
                _CACHE[url] = payload
        else:
            # a dead peer is the tolerated case, not an error: serve its
            # last good payload
            _telem.inc("telemetry.federation.stale_ranks")
            with _LOCK:
                payload = _CACHE.get(url)
            if payload is None:
                _LOG.debug("federation: peer %s unreachable, no cached "
                           "payload", url)
                missing.append(url)
                continue
            payload = dict(payload, stale=True)
            stale.append(url)
        resolved.append(payload)
    # the local payload is built LAST (so this scrape's own federation
    # counters are in) but inserted FIRST: on a self-reported-rank
    # collision the local exporter keeps its own identity and the peer is
    # the one bumped — the rank label must agree with /metrics
    by_rank = {}
    local = _export.snapshot_payload()
    _insert_rank(by_rank, int(local.get("rank", 0)), local)
    for payload in resolved:
        _insert_rank(by_rank, int(payload.get("rank", len(by_rank))),
                     payload)
    merged = merge_snapshots([p.get("snapshot", {})
                              for _r, p in sorted(by_rank.items())])
    return {
        "ts": time.time(),
        "trace_id": _telem.trace_id(),
        "rank": _telem.safe_rank(),
        "workers": len(by_rank),
        "stale_ranks": stale,
        "missing": missing,
        "ranks": {str(r): p for r, p in sorted(by_rank.items())},
        "merged": merged,
    }


def fleet_metrics_text():
    """Every rank's Prometheus text in one body: per-rank series keep
    their rank label; duplicate HELP/TYPE header lines (same metric on
    several ranks) are emitted once. None when telemetry is disabled."""
    from . import export as _export
    fleet = fleet_snapshot()
    if fleet is None:
        return None
    seen = set()
    lines = []
    for rank, payload in sorted((int(r), p)
                                for r, p in fleet["ranks"].items()):
        text = _export.prometheus_text(payload.get("snapshot", {}),
                                       rank=rank)
        for line in text.splitlines():
            if line.startswith("#"):
                if line in seen:
                    continue
                seen.add(line)
            lines.append(line)
    lines.append("# HELP mxnet_tpu_fleet_workers ranks in this scrape")
    lines.append("# TYPE mxnet_tpu_fleet_workers gauge")
    lines.append("mxnet_tpu_fleet_workers %d" % fleet["workers"])
    return "\n".join(lines) + "\n"
