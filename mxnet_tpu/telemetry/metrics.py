"""Metric primitives + registry for the runtime telemetry subsystem.

The reference ships aggregate runtime statistics inside its profiler
(src/profiler/aggregate_stats.cc); TPU-native observability needs more than
per-op timings — cache behavior, comm volume, sync stalls, memory pressure —
so telemetry is its own thread-safe registry of named counters, gauges, and
histograms, sampled by the instrumented hot paths and exported as JSON, a
human table, or a chrome://tracing dump (see trace.py).

All metric types are cheap under the GIL and take a per-metric lock for the
multi-writer cases (histogram/gauge); creation goes through the registry's
lock so concurrent get-or-create races resolve to one object.
"""
from __future__ import annotations

import bisect
import json
import threading

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "DEFAULT_MS_BOUNDS"]

# exponential bucket bounds tuned for millisecond latencies: 0.01 ms (jit
# cache hit) through ~100 s (cold XLA compile of a big model)
DEFAULT_MS_BOUNDS = tuple(0.01 * (4.0 ** i) for i in range(12))


class Counter:
    """Monotonically increasing count (calls, bytes, cache hits)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        return self._value

    def snapshot(self):
        # under the metric lock: a live exporter scrape racing inc() from a
        # step thread must see a committed value, not a partial += on a
        # future non-GIL runtime
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value with a high-watermark (memory in use, queue
    depth). `set` keeps the max ever seen so transient peaks survive."""

    __slots__ = ("name", "_value", "_max", "_lock")

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._max = 0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value
            if value > self._max:
                self._max = value

    @property
    def value(self):
        return self._value

    @property
    def max(self):
        return self._max

    def snapshot(self):
        # under the metric lock: value and max are a PAIR — a scrape racing
        # set() must never observe a fresh value with a stale max (torn
        # watermark), so the exporter's reads stay atomic per metric
        with self._lock:
            return {"value": self._value, "max": self._max}


class Histogram:
    """Exponential-bucket latency/size distribution."""

    __slots__ = ("name", "bounds", "_buckets", "_count", "_sum", "_min",
                 "_max", "_lock")

    def __init__(self, name, bounds=None):
        self.name = name
        self.bounds = tuple(sorted(bounds)) if bounds else DEFAULT_MS_BOUNDS
        self._buckets = [0] * (len(self.bounds) + 1)  # last = +inf overflow
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def snapshot(self):
        with self._lock:
            buckets = {}
            for bound, n in zip(self.bounds, self._buckets):
                if n:
                    buckets["le_%g" % bound] = n
            if self._buckets[-1]:
                buckets["le_inf"] = self._buckets[-1]
            # bounds travel with the sparse buckets: a quantile estimator
            # needs the rank-holding bucket's TRUE lower edge, which the
            # present-buckets dict alone cannot name when the bucket
            # below it is empty (and therefore omitted)
            return {"count": self._count, "sum": self._sum,
                    "min": self._min, "max": self._max,
                    "avg": (self._sum / self._count) if self._count else None,
                    "bounds": list(self.bounds),
                    "buckets": buckets}


class Registry:
    """Thread-safe get-or-create store of named metrics."""

    def __init__(self):
        self._metrics = {}
        # deferred import: analysis loads after telemetry in the package
        # __init__, and lockguard only needs ..base at module level
        from ..analysis import lockguard
        self._lock = lockguard.lock("telemetry.registry")

    def _get_or_create(self, name, cls, *args):
        metric = self._metrics.get(name)
        if metric is None:
            with self._lock:
                metric = self._metrics.get(name)
                if metric is None:
                    metric = cls(name, *args)
                    self._metrics[name] = metric
        if not isinstance(metric, cls):
            raise TypeError(
                "telemetry metric %r already registered as %s, requested %s"
                % (name, type(metric).__name__, cls.__name__))
        return metric

    def counter(self, name):
        return self._get_or_create(name, Counter)

    def gauge(self, name):
        return self._get_or_create(name, Gauge)

    def histogram(self, name, bounds=None):
        if bounds is not None:
            return self._get_or_create(name, Histogram, bounds)
        return self._get_or_create(name, Histogram)

    def get(self, name):
        return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    # -- export --------------------------------------------------------
    def snapshot(self):
        """{"counters": {name: int}, "gauges": {name: {value,max}},
        "histograms": {name: {count,sum,min,max,avg,buckets}}}."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            else:
                out["histograms"][name] = m.snapshot()
        return out

    def dumps(self, format="table"):
        if format not in ("table", "json"):
            raise ValueError(
                "telemetry dumps format must be 'table' or 'json', got %r"
                % (format,))
        snap = self.snapshot()
        if format == "json":
            return json.dumps(snap)
        lines = []
        if snap["counters"]:
            lines.append("%-48s %16s" % ("Counter", "Value"))
            for name, v in snap["counters"].items():
                lines.append("%-48s %16d" % (name, v))
        if snap["gauges"]:
            lines.append("%-48s %16s %16s" % ("Gauge", "Value", "Max"))
            for name, g in snap["gauges"].items():
                lines.append("%-48s %16g %16g" % (name, g["value"], g["max"]))
        if snap["histograms"]:
            lines.append("%-48s %10s %12s %12s %12s %12s" %
                         ("Histogram", "Count", "Sum", "Avg", "Min", "Max"))
            for name, h in snap["histograms"].items():
                lines.append("%-48s %10d %12.3f %12.3f %12.3f %12.3f" %
                             (name, h["count"], h["sum"], h["avg"] or 0.0,
                              h["min"] or 0.0, h["max"] or 0.0))
        return "\n".join(lines)
