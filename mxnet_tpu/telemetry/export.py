"""Live metrics export: Prometheus `/metrics` endpoint + JSONL streamer.

Until now telemetry was post-hoc: `dump()` after the run — which is exactly
when a wedged fleet can no longer produce it. This module makes the
registry observable WHILE the run is alive, with two transports:

* **HTTP endpoint** — ``MXNET_TPU_METRICS_PORT=<port>`` (or
  `start_http_server(port)`) serves, from one daemon thread:
  - ``/metrics`` — Prometheus text exposition (counters as counters,
    gauges as value+``_max`` watermark pairs, histograms as cumulative
    ``_bucket{le=...}``/``_sum``/``_count`` series), every sample labeled
    with this worker's rank;
  - ``/snapshot`` — the raw `telemetry.snapshot()` dict plus rolling
    step-latency quantiles, rank, and the run trace id (what
    `tools/mxtop.py` polls);
  - ``/requests`` — the completed per-request trace ring
    (`telemetry.request_trace`): one timeline per served request;
  - ``/fleet/metrics`` / ``/fleet/snapshot`` — the WHOLE fleet through
    one scrape (`telemetry.federation`): rank-labeled series /
    merged+per-rank payloads, stale-rank tolerant;
  - ``/healthz`` — liveness.
* **JSONL stream** — ``MXNET_TPU_METRICS_STREAM=<path>`` appends one
  `/snapshot`-shaped JSON line every ``MXNET_TPU_METRICS_STREAM_S``
  (default 5) seconds from a daemon thread — the no-port transport for
  batch fleets whose only artifact channel is a file (mxtop tails it).

Both transports read through `Registry.snapshot()`, i.e. under the
registry lock with per-metric-atomic reads — a scrape racing a step thread
sees a consistent registry. Both are OFF by default and fully inert under
``MXNET_TPU_TELEMETRY=0``: no thread is started and no port is bound even
when the env vars are set.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["prometheus_text", "snapshot_payload", "requests_payload",
           "parse_prometheus_text",
           "histogram_quantiles", "start_http_server", "stop_http_server",
           "start_stream", "stop_stream", "maybe_start_from_env",
           "MetricsServer", "SnapshotStreamer",
           "default_stream_interval_s"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

_PREFIX = "mxnet_tpu_"


def _telem():
    from .. import telemetry
    return telemetry


def default_stream_interval_s():
    try:
        return max(0.05,
                   float(os.environ.get("MXNET_TPU_METRICS_STREAM_S", "5")))
    except (TypeError, ValueError):
        return 5.0


# ------------------------------------------------------------- text format
def _sanitize(name):
    return _PREFIX + "".join(
        c if c.isalnum() or c == "_" else "_" for c in name)


def _fmt_value(v):
    if v is None:
        return "NaN"
    if isinstance(v, float):
        return repr(v)
    return str(v)


def _hist_bounds(buckets):
    """Snapshot bucket keys ("le_0.01", ..., "le_inf") ordered by bound."""
    def key(k):
        raw = k[len("le_"):]
        return float("inf") if raw == "inf" else float(raw)
    return sorted(buckets, key=key)


# quantiles derived for every histogram by the exporter; the rolling
# step-time windows (telemetry.anomaly) stay the EXACT source for step
# sites — this is the estimate that covers everything else a scraper sees
_QUANTILES = (0.5, 0.99)


def histogram_quantiles(h, qs=_QUANTILES):
    """Estimate quantiles from a histogram snapshot's sparse buckets.

    NOTE the input format: `Histogram.snapshot()` emits PER-BUCKET counts
    (non-cumulative; empty buckets omitted) — not the cumulative `le`
    series `/metrics` renders. The math is `prometheus
    histogram_quantile` semantics on top of that (linear interpolation
    inside the bucket holding the target rank; the overflow bucket
    answers with the observed max), sharpened with the snapshot's exact
    min/max where the registry recorded them. Returns
    {"p50": ..., "p99": ...} or None for an empty histogram.

    Before this, scrapers got exact p50/p99 only for the step sites whose
    rolling windows `telemetry.anomaly` keeps; every other histogram
    (ckpt.save_ms, serve.ttft_ms, compile_ms, ...) exported buckets and
    left the tail math to the server. Now the quantiles ride the scrape
    itself (`/metrics` gauge series, `/snapshot`/stream payloads)."""
    count = h.get("count") or 0
    if not count:
        return None
    buckets = h.get("buckets", {})
    # densify over the histogram's full bound list when the snapshot
    # carries it: the rank-holding bucket's true lower edge may be an
    # EMPTY (hence omitted) bucket's bound — interpolating from the
    # previous present bound instead would pull the estimate below every
    # observation in the bucket
    bounds = h.get("bounds")
    if bounds:
        items = [(float(b), buckets.get("le_%g" % b, 0)) for b in bounds]
        items.append((float("inf"), buckets.get("le_inf", 0)))
    else:  # legacy dump without bounds: present buckets only
        items = []
        for k in _hist_bounds(buckets):
            raw = k[len("le_"):]
            bound = float("inf") if raw == "inf" else float(raw)
            items.append((bound, buckets[k]))
    mn, mx = h.get("min"), h.get("max")
    out = {}
    for q in qs:
        target = q * count
        cum = 0
        lower = 0.0
        val = None
        for bound, n in items:
            if cum + n >= target:
                if bound == float("inf"):
                    val = mx
                else:
                    val = lower + (bound - lower) * (target - cum) / n
                break
            cum += n
            if bound != float("inf"):
                lower = bound
        if val is None:
            val = mx
        if val is not None:
            if mn is not None:
                val = max(val, mn)
            if mx is not None:
                val = min(val, mx)
        out["p%g" % (q * 100)] = val
    return out


def prometheus_text(snap=None, rank=None):
    """Render a `telemetry.snapshot()` dict in the Prometheus text
    exposition format (0.0.4). Each HELP line carries the original dotted
    metric name, so a scrape is parseable back counter-for-counter
    (`parse_prometheus_text` is the inverse — the parity tests use it)."""
    telem = _telem()
    if snap is None:
        snap = telem.snapshot()
    if rank is None:
        rank = telem.safe_rank()
    label = '{rank="%d"}' % int(rank)
    lines = []
    for name, value in snap.get("counters", {}).items():
        san = _sanitize(name)
        lines.append("# HELP %s %s" % (san, name))
        lines.append("# TYPE %s counter" % san)
        lines.append("%s%s %s" % (san, label, _fmt_value(value)))
    for name, g in snap.get("gauges", {}).items():
        san = _sanitize(name)
        lines.append("# HELP %s %s" % (san, name))
        lines.append("# TYPE %s gauge" % san)
        lines.append("%s%s %s" % (san, label, _fmt_value(g.get("value"))))
        lines.append("# TYPE %s_max gauge" % san)
        lines.append("%s_max%s %s" % (san, label, _fmt_value(g.get("max"))))
    for name, h in snap.get("histograms", {}).items():
        san = _sanitize(name)
        lines.append("# HELP %s %s" % (san, name))
        lines.append("# TYPE %s histogram" % san)
        cum = 0
        buckets = h.get("buckets", {})
        for k in _hist_bounds(buckets):
            bound = k[len("le_"):]
            if bound == "inf":
                continue
            cum += buckets[k]
            lines.append('%s_bucket{rank="%d",le="%s"} %d'
                         % (san, int(rank), bound, cum))
        lines.append('%s_bucket{rank="%d",le="+Inf"} %d'
                     % (san, int(rank), h.get("count", 0)))
        lines.append("%s_sum%s %s" % (san, label, _fmt_value(h.get("sum"))))
        lines.append("%s_count%s %s" % (san, label,
                                        _fmt_value(h.get("count"))))
        # derived quantiles as gauge series (<name>_p50/<name>_p99): the
        # sparse buckets stay authoritative; these save every scraper the
        # histogram_quantile() reimplementation and carry the exact
        # min/max clamp the raw buckets cannot express
        quants = histogram_quantiles(h)
        for key, value in sorted((quants or {}).items()):
            lines.append("# TYPE %s_%s gauge" % (san, key))
            lines.append("%s_%s%s %s" % (san, key, label,
                                         _fmt_value(value)))
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text):
    """Parse a `/metrics` scrape back into {original_name: value} for the
    counter series (the parity-test inverse of `prometheus_text`). HELP
    lines map the sanitized series name back to the dotted original."""
    help_map = {}
    types = {}
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            san, _, orig = rest.partition(" ")
            help_map[san] = orig
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            san, _, kind = rest.partition(" ")
            types[san] = kind
        elif line and not line.startswith("#"):
            series, _, value = line.rpartition(" ")
            san = series.split("{", 1)[0]
            if types.get(san) == "counter" and san in help_map:
                out[help_map[san]] = int(float(value))
    return out


def snapshot_payload():
    """The JSON body both live transports emit: the registry snapshot plus
    identity (rank, trace id) and rolling step-latency quantiles."""
    telem = _telem()
    from . import anomaly
    snap = telem.snapshot()
    hist_q = {}
    for name, h in snap.get("histograms", {}).items():
        q = histogram_quantiles(h)
        if q:
            hist_q[name] = q
    from . import ledger, profiling
    return {
        "ts": time.time(),
        "rank": telem.safe_rank(),
        "trace_id": telem.trace_id(),
        "snapshot": snap,
        "step_quantiles": anomaly.quantiles_all(),
        "hist_quantiles": hist_q,
        "flight_steps": len(_flight_recorder()),
        # the HBM ledger: per-scope bytes, per-program static footprints,
        # and the last device reconcile (what mxtop --mem / parse_log
        # --mem tabulate)
        "memory": {
            "scopes": ledger.scopes(),
            "programs": ledger.programs(),
            "reconcile": ledger.last_reconcile(),
        },
        "profiles": profiling.records(),
    }


def requests_payload():
    """The `/requests` body: this rank's completed `RequestTrace` ring
    (identity-stamped so a dump from any rank names its run)."""
    telem = _telem()
    from . import request_trace
    return {
        "ts": time.time(),
        "rank": telem.safe_rank(),
        "trace_id": telem.trace_id(),
        "requests": request_trace.records(),
    }


def _flight_recorder():
    from . import flight
    return flight._RECORDER


# ------------------------------------------------------------- HTTP server
class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-telemetry"

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        path, _, query = self.path.partition("?")
        try:
            if path == "/profile":
                # on-demand capture (rate-limited in profiling): blocks
                # THIS handler thread for the window; other scrapes keep
                # flowing (ThreadingHTTPServer)
                from urllib.parse import parse_qs

                from . import profiling
                ms = None
                raw = parse_qs(query).get("ms", [None])[0]
                if raw is not None:
                    try:
                        ms = int(raw)
                    except ValueError:
                        ms = None
                out = profiling.capture_profile(ms=ms)
                if out is None:
                    body = json.dumps(
                        {"ok": False, "error": "rate_limited",
                         "min_interval_s": profiling.min_interval_s()},
                    ).encode("utf-8")
                    self.send_response(429)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = json.dumps({"ok": True, "path": out}).encode("utf-8")
                ctype = "application/json"
            elif path == "/metrics":
                body = prometheus_text().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("/", "/snapshot"):
                body = json.dumps(snapshot_payload()).encode("utf-8")
                ctype = "application/json"
            elif path == "/requests":
                body = json.dumps(requests_payload()).encode("utf-8")
                ctype = "application/json"
            elif path == "/fleet/metrics":
                from . import federation
                body = (federation.fleet_metrics_text() or "").encode(
                    "utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/fleet/snapshot":
                from . import federation
                body = json.dumps(federation.fleet_snapshot()).encode(
                    "utf-8")
                ctype = "application/json"
            elif path == "/healthz":
                body = b"ok\n"
                ctype = "text/plain"
            else:
                self.send_error(404, "unknown path %r" % path)
                return
        except Exception as exc:  # noqa: BLE001 — a scrape bug must not
            # take down the serving thread
            self.send_error(500, "telemetry export failed: %s" % exc)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        _LOG.debug("metrics http: " + format, *args)


class MetricsServer:
    """ThreadingHTTPServer on a daemon thread; `close()` releases the
    port synchronously (tests bind successive free ports)."""

    def __init__(self, port, host="0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mxnet_tpu_metrics_http", daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


class SnapshotStreamer:
    """Daemon thread appending one `snapshot_payload()` JSON line to `path`
    every `interval_s` seconds (and once more on `close()`, so short runs
    still leave a final line)."""

    def __init__(self, path, interval_s=None):
        self.path = os.path.abspath(path)
        self.interval_s = (default_stream_interval_s()
                           if interval_s is None else float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mxnet_tpu_metrics_stream", daemon=True)
        self._thread.start()

    def _write_line(self):
        try:
            line = json.dumps(snapshot_payload())
            with open(self.path, "a") as f:
                f.write(line + "\n")
        except Exception as exc:  # noqa: BLE001 — a full disk must not kill
            # the streamer (the run matters more than its metrics)
            _LOG.debug("metrics stream write failed: %s", exc)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self._write_line()

    def close(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._write_line()  # final flush: the run's last word


# ---------------------------------------------------------- module control
_STATE = {"server": None, "streamer": None, "atexit_registered": False}
_STATE_LOCK = threading.Lock()


def start_http_server(port=None, host=None):
    """Start (or return the running) metrics endpoint. Returns None —
    binding nothing — when telemetry is disabled or no port is configured.
    Binds MXNET_TPU_METRICS_HOST (default 0.0.0.0 — remote scraping is
    the point of a Prometheus endpoint; set 127.0.0.1 on shared-tenant
    networks, the payload names checkpoints and resilience activity)."""
    if not _telem().ENABLED:
        return None
    if host is None:
        host = os.environ.get("MXNET_TPU_METRICS_HOST") or "0.0.0.0"
    if port is None:
        raw = os.environ.get("MXNET_TPU_METRICS_PORT", "")
        if not raw or raw == "0":
            return None
        try:
            port = int(raw)
            if not 0 < port < 65536:
                raise ValueError("out of range")
        except ValueError:
            # a typo in the env var must not kill `import mxnet_tpu`
            _LOG.warning("telemetry: ignoring malformed "
                         "MXNET_TPU_METRICS_PORT=%r (want a port number)",
                         raw)
            return None
    with _STATE_LOCK:
        if _STATE["server"] is None:
            _STATE["server"] = MetricsServer(port, host=host)
            _LOG.info("telemetry: /metrics endpoint on port %d",
                      _STATE["server"].port)
        return _STATE["server"]


def stop_http_server():
    with _STATE_LOCK:
        server, _STATE["server"] = _STATE["server"], None
    if server is not None:
        server.close()


def start_stream(path=None, interval_s=None):
    """Start (or return the running) JSONL snapshot streamer. Returns None
    when telemetry is disabled or no path is configured."""
    if not _telem().ENABLED:
        return None
    if path is None:
        path = os.environ.get("MXNET_TPU_METRICS_STREAM", "")
        if not path:
            return None
    with _STATE_LOCK:
        if _STATE["streamer"] is None:
            _STATE["streamer"] = SnapshotStreamer(path,
                                                  interval_s=interval_s)
            _LOG.info("telemetry: streaming snapshots to %s",
                      _STATE["streamer"].path)
        return _STATE["streamer"]


def stop_stream():
    with _STATE_LOCK:
        streamer, _STATE["streamer"] = _STATE["streamer"], None
    if streamer is not None:
        streamer.close()


def maybe_start_from_env():
    """Import-time hook: start whichever transports the env configures.
    Inert (no thread, no port) unless telemetry is enabled AND a knob is
    set; binding failures log a warning instead of killing the import (two
    workers on one host sharing a port must not crash the run)."""
    server = streamer = None
    # broad except: NOTHING a bad env knob provokes (bind failure, bad
    # port value, read-only stream path) may crash the interpreter's
    # import of mxnet_tpu
    try:
        server = start_http_server()
    except Exception as exc:  # noqa: BLE001 — see above
        _LOG.warning("telemetry: could not bind MXNET_TPU_METRICS_PORT: %s",
                     exc)
    try:
        streamer = start_stream()
    except Exception as exc:  # noqa: BLE001 — see above
        _LOG.warning("telemetry: could not open MXNET_TPU_METRICS_STREAM: "
                     "%s", exc)
    if server is not None or streamer is not None:
        # the streamer's close() writes the FINAL line (a run shorter than
        # one interval would otherwise leave an empty stream file); the
        # server close releases the port promptly on interpreter exit.
        # Registered once — enable() re-runs this path freely.
        with _STATE_LOCK:
            need = not _STATE["atexit_registered"]
            _STATE["atexit_registered"] = True
        if need:
            import atexit
            atexit.register(stop_stream)
            atexit.register(stop_http_server)
    return server, streamer
