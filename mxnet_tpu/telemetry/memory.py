"""Best-effort device-memory watermark sampling.

PjRt exposes per-device allocator statistics through
`Device.memory_stats()` (TPU/GPU; the CPU backend usually returns None or
raises NotImplementedError). The sampler folds whatever is available into
gauges:

    memory.<platform><id>.bytes_in_use        current allocation
    memory.<platform><id>.peak_bytes_in_use   allocator high-watermark

`maybe_sample` rate-limits to one device query per MIN_INTERVAL_S so
per-step instrumentation can call it unconditionally.

`device_report()` is the post-mortem variant: instead of gauges it returns
one structured dict per device — platform, allocator stats, and live
buffer count/bytes attributed per device from `jax.live_arrays()` — the
PjRt state the resilience watchdog staples onto a `StallError` next to the
host span dump.
"""
from __future__ import annotations

import time

__all__ = ["sample", "maybe_sample", "device_report"]

MIN_INTERVAL_S = 1.0
_last_sample = [0.0]


def sample(registry):
    """Query every jax device once; returns the number of devices that
    reported stats (0 when the backend has none — CPU, or jax absent)."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        prefix = "memory.%s%d" % (d.platform, d.id)
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            registry.gauge(prefix + ".bytes_in_use").set(int(in_use))
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            registry.gauge(prefix + ".peak_bytes_in_use").set(int(peak))
        reported += 1
    return reported


def maybe_sample(registry):
    now = time.monotonic()
    if now - _last_sample[0] < MIN_INTERVAL_S:
        return 0
    _last_sample[0] = now
    return sample(registry)


def device_report():
    """Best-effort per-device PjRt state for post-mortems.

    Returns a list of dicts, one per jax device:
    ``{"device": "tpu0", "platform": "tpu", "bytes_in_use": ...,
    "peak_bytes_in_use": ..., "num_allocs": ..., "live_buffers": N,
    "live_bytes": B}`` — allocator stats from `Device.memory_stats()`
    (absent keys omitted), live buffers attributed from
    `jax.live_arrays()` shard placement. Every probe is best-effort: a
    backend that exposes none of it still yields a row with the device
    name, so the report never raises."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return []
    live_count = {}
    live_bytes = {}
    try:
        for arr in jax.live_arrays():
            shards = getattr(arr, "addressable_shards", None) or []
            for shard in shards:
                dev = shard.device
                live_count[dev] = live_count.get(dev, 0) + 1
                data = getattr(shard, "data", None)
                nbytes = getattr(data, "nbytes", None)
                if nbytes is not None:
                    live_bytes[dev] = live_bytes.get(dev, 0) + int(nbytes)
    except Exception:  # live-array walk is diagnostic only
        pass
    report = []
    for d in devices:
        entry = {"device": "%s%d" % (d.platform, d.id),
                 "platform": d.platform}
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        for key in ("bytes_in_use", "peak_bytes_in_use", "num_allocs"):
            val = (stats or {}).get(key)
            if val is not None:
                entry[key] = int(val)
        if d in live_count:
            entry["live_buffers"] = live_count[d]
            entry["live_bytes"] = live_bytes.get(d, 0)
        report.append(entry)
    return report
