"""Best-effort device-memory watermark sampling.

PjRt exposes per-device allocator statistics through
`Device.memory_stats()` (TPU/GPU; the CPU backend usually returns None or
raises NotImplementedError). The sampler folds whatever is available into
gauges:

    memory.<platform><id>.bytes_in_use        current allocation
    memory.<platform><id>.peak_bytes_in_use   allocator high-watermark

`maybe_sample` rate-limits to one device query per MIN_INTERVAL_S so
per-step instrumentation can call it unconditionally.
"""
from __future__ import annotations

import time

__all__ = ["sample", "maybe_sample"]

MIN_INTERVAL_S = 1.0
_last_sample = [0.0]


def sample(registry):
    """Query every jax device once; returns the number of devices that
    reported stats (0 when the backend has none — CPU, or jax absent)."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        prefix = "memory.%s%d" % (d.platform, d.id)
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            registry.gauge(prefix + ".bytes_in_use").set(int(in_use))
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            registry.gauge(prefix + ".peak_bytes_in_use").set(int(peak))
        reported += 1
    return reported


def maybe_sample(registry):
    now = time.monotonic()
    if now - _last_sample[0] < MIN_INTERVAL_S:
        return 0
    _last_sample[0] = now
    return sample(registry)
