"""Paged KV-cache allocator: fixed-size blocks with a free-list.

The naive decode cache (`models.llama.init_kv_cache`) is a
(batch, max_seq_len) rectangle per stream — a 64-token chat in an 8k-context
model wastes 99% of its rows, and the rectangle's batch dim is frozen at
allocation, which is exactly what continuous batching cannot have. Here KV
lives in ONE physical pool per layer, carved into fixed-size blocks
(vLLM's PagedAttention layout, sized by ``MXNET_TPU_SERVE_KV_BLOCKS`` ×
``MXNET_TPU_SERVE_KV_BLOCK`` tokens): a stream owns exactly the blocks its
context fills, via a block table the jitted programs use to gather/scatter
(`parallel.flash_attention.paged_attention`), and finished streams return
their blocks to a free-list for immediate reuse — fragmentation is
impossible by construction because every block is interchangeable.

Exhaustion is a *verdict*, not a crash: `alloc` either reserves every block
the caller asked for or raises a structured `Overloaded` having reserved
nothing, so admission control can shed the request (or leave it queued)
while the streams already running keep their memory. Freed blocks are not
zeroed — a reused block is fully overwritten up to its new owner's length,
and positions past that length are masked out of every gather.

Telemetry: ``serve.kv.blocks_in_use`` gauge (watermark = peak pool
pressure), ``serve.kv.allocs`` / ``serve.kv.freed_blocks`` /
``serve.kv.exhausted`` counters.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from .. import telemetry as _telem
from .errors import Overloaded

__all__ = ["KVBlockPool", "default_num_blocks", "default_block_size"]


def default_num_blocks():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_KV_BLOCKS", "256")))
    except (TypeError, ValueError):
        return 256


def default_block_size():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_KV_BLOCK", "16")))
    except (TypeError, ValueError):
        return 16


class KVBlockPool:
    """Physical paged KV pool + block accounting for one serving replica.

    Owns the per-layer pool arrays (`models.llama.init_kv_pools` layout)
    and the stream → block-table map. The jitted programs treat the arrays
    functionally; `update()` swaps in each program's returned pools (the
    programs donate the inputs, so the swap is also the memory's lifetime).
    """

    def __init__(self, cfg, num_blocks=None, block_size=None, dtype=None):
        from ..models.llama import init_kv_pools
        self.cfg = cfg
        self.num_blocks = int(num_blocks or default_num_blocks())
        self.block_size = int(block_size or default_block_size())
        self._dtype = dtype
        self.pools = init_kv_pools(cfg, self.num_blocks, self.block_size,
                                   dtype=dtype)
        # LIFO free-list: a just-freed (cache-warm) block is reused first
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}           # stream_id -> [block ids]
        self._lock = threading.Lock()

    # ------------------------------------------------------------- geometry
    def blocks_for(self, n_tokens):
        """Blocks needed to hold an n_tokens context."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    @property
    def free_blocks(self):
        with self._lock:
            return len(self._free)

    @property
    def blocks_in_use(self):
        with self._lock:
            return self.num_blocks - len(self._free)

    # ----------------------------------------------------------- allocation
    def alloc(self, stream_id, n_tokens):
        """Grow `stream_id`'s block table to cover `n_tokens` positions.

        All-or-nothing: raises `Overloaded(reason="kv_exhausted")` — having
        reserved NOTHING — when the free-list is short, so a rejected
        admission never strands half a context in the pool."""
        need_total = self.blocks_for(n_tokens)
        with self._lock:
            table = self._tables.get(stream_id, [])
            grow = need_total - len(table)
            if grow <= 0:
                return list(table)
            if grow > len(self._free):
                # reserve NOTHING on failure — not even an empty table
                # entry: rejected stream ids are uuids that never return,
                # so a leftover entry would leak one dict slot per shed
                free = len(self._free)
                _telem.inc("serve.kv.exhausted")
                raise Overloaded(
                    "KV pool exhausted: stream %r needs %d more block(s) "
                    "(%d tokens) but only %d of %d are free"
                    % (stream_id, grow, n_tokens, free, self.num_blocks),
                    reason="kv_exhausted", kv_free_blocks=free,
                    kv_needed_blocks=grow)
            table = table + [self._free.pop() for _ in range(grow)]
            self._tables[stream_id] = table
            in_use = self.num_blocks - len(self._free)
        _telem.inc("serve.kv.allocs")
        _telem.set_gauge("serve.kv.blocks_in_use", in_use)
        return list(table)

    def free(self, stream_id):
        """Return the stream's blocks to the free-list (idempotent)."""
        with self._lock:
            table = self._tables.pop(stream_id, None)
            if not table:
                return 0
            self._free.extend(reversed(table))
            in_use = self.num_blocks - len(self._free)
        _telem.inc("serve.kv.freed_blocks", len(table))
        _telem.set_gauge("serve.kv.blocks_in_use", in_use)
        return len(table)

    def table(self, stream_id, width):
        """The stream's block table as a width-`width` int32 array, padded
        with the `num_blocks` sentinel (dropped writes / masked reads).
        Truncates past `width`: a prefill bucket's table only names the
        blocks its positions can touch, even when the stream reserved its
        worst-case context up front."""
        with self._lock:
            blocks = list(self._tables.get(stream_id, ()))[:width]
        out = np.full(width, self.num_blocks, np.int32)
        out[:len(blocks)] = blocks
        return out

    def owned_blocks(self, stream_id):
        with self._lock:
            return list(self._tables.get(stream_id, ()))

    # -------------------------------------------------------------- storage
    def update(self, new_pools):
        """Adopt the pools a prefill/decode program returned (the program
        donated the previous arrays)."""
        self.pools = new_pools

    def reconcile(self):
        """Rebuild the free-list as the exact complement of every live
        table. Recovery calls this because an async fault (the watchdog's
        StallError lands at any bytecode) can tear alloc/free mid-flight:
        blocks popped from the free-list but not yet committed to a
        table — or popped from a table but not yet returned — are in
        NEITHER structure and would otherwise leak forever, shrinking
        effective pool capacity with every stall. Returns the number of
        blocks recovered (0 when nothing was torn)."""
        with self._lock:
            owned = {b for table in self._tables.values() for b in table}
            before = len(self._free)
            self._free = [b for b in range(self.num_blocks - 1, -1, -1)
                          if b not in owned]
            recovered = len(self._free) - before
            in_use = self.num_blocks - len(self._free)
        if recovered:
            _telem.inc("serve.kv.reconciled_blocks", recovered)
            _telem.set_gauge("serve.kv.blocks_in_use", in_use)
        return recovered

    def ensure_storage(self):
        """Heal donation wreckage after a fault: an async StallError can
        land between a donating program call and `update`, leaving
        `pools` pointing at deleted buffers. Recovery requeues every
        stream for re-prefill, so the CONTENT is worthless anyway — the
        arrays just have to be alive again. Returns True when the pools
        were re-materialized."""
        import jax
        from ..models.llama import init_kv_pools
        leaves = jax.tree_util.tree_leaves(self.pools)
        if not any(isinstance(x, jax.Array) and x.is_deleted()
                   for x in leaves):
            return False
        self.pools = init_kv_pools(self.cfg, self.num_blocks,
                                   self.block_size, dtype=self._dtype)
        _telem.inc("serve.kv.storage_resets")
        return True
