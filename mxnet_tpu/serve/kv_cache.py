"""Paged KV-cache allocator: refcounted fixed-size blocks + prefix sharing.

The naive decode cache (`models.llama.init_kv_cache`) is a
(batch, max_seq_len) rectangle per stream — a 64-token chat in an 8k-context
model wastes 99% of its rows, and the rectangle's batch dim is frozen at
allocation, which is exactly what continuous batching cannot have. Here KV
lives in ONE physical pool per layer, carved into fixed-size blocks
(vLLM's PagedAttention layout, sized by ``MXNET_TPU_SERVE_KV_BLOCKS`` ×
``MXNET_TPU_SERVE_KV_BLOCK`` tokens): a stream owns exactly the blocks its
context fills, via a block table the jitted programs use to gather/scatter
(`parallel.flash_attention.paged_attention`), and finished streams return
their blocks to a free-list for immediate reuse — fragmentation is
impossible by construction because every block is interchangeable.

Blocks are **refcounted**, because a block can now have several owners:
KV at position p depends only on the token sequence 0..p, so a FULL block
of a prompt whose tokens (and whole preceding context) match another
stream's is the *same* block — N concurrent users of one system prompt
share its blocks instead of each prefilling their own copy. The pool
hash-conses full prompt-prefix blocks in a chain-keyed index (node key =
(parent, block tokens) — the chain IS the hash, so equal-token blocks
under different prefixes never unify) and `admit` finds the block-aligned
longest-common-prefix at admission: matched full blocks join the new
stream's table with a refcount bump and prefill SKIPS their positions;
when the match ends mid-block, the divergence block is **copied-on-write**
(one fresh private block + a device block-copy of the partially-matched
source, counted ``serve.prefix.cow``) so the stream recomputes only from
its true divergence point. Shared blocks are never written after their
prefill (decode appends strictly past the prompt), so sharing needs no
write barriers — only exact refcounts. The index holds its own reference
per cached block (a finished stream's prefix stays warm for the next
user) and evicts least-recently-matched leaf entries when allocation
would otherwise fail.

Exhaustion is a *verdict*, not a crash: `alloc`/`admit` either reserve
every block the caller asked for or raise a structured `Overloaded` having
reserved nothing, so admission control can shed the request (or leave it
queued) while the streams already running keep their memory. Freed blocks
are not zeroed — a reused block is fully overwritten up to its new owner's
length, and positions past that length are masked out of every gather.

Telemetry: ``serve.kv.blocks_in_use`` gauge (watermark = peak pool
pressure), ``serve.kv.allocs`` / ``serve.kv.freed_blocks`` /
``serve.kv.exhausted`` counters, and the prefix-sharing story:
``serve.prefix.lookups`` / ``hits`` / ``blocks_shared`` (each one a
whole block of prefill skipped AND a block of HBM saved while shared) /
``cow`` / ``inserted`` / ``evictions``, plus the ``serve.prefix.blocks``
gauge (blocks currently pinned by the index). Byte-level attribution
(ISSUE 16): ``serve.kv.bytes`` / ``serve.kv.draft_bytes`` (blocks in use ×
bytes/block) and ``serve.prefix.bytes`` gauges, the pool's storage bytes
accounted to the HBM ledger (scope ``kv_pool`` / ``kv_draft``; prefix
bytes as the ``prefix_cache`` overlay), and an `Overloaded(kv_exhausted)`
that carries the full ledger breakdown — the shed verdict names WHOSE
bytes crowded the pool out.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from .. import telemetry as _telem
from .errors import Overloaded

__all__ = ["KVBlockPool", "default_num_blocks", "default_block_size",
           "prefix_sharing_enabled"]


def default_num_blocks():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_KV_BLOCKS", "256")))
    except (TypeError, ValueError):
        return 256


def default_block_size():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_KV_BLOCK", "16")))
    except (TypeError, ValueError):
        return 16


def prefix_sharing_enabled():
    return os.environ.get("MXNET_TPU_SERVE_PREFIX", "1").lower() not in (
        "0", "false", "off")


class _PrefixNode:
    """One hash-consed full block of cached prompt prefix."""

    __slots__ = ("key", "parent", "tokens", "block", "children", "lru")

    def __init__(self, key, parent, tokens, block, lru):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.block = block
        self.children = set()       # child node keys
        self.lru = lru


class KVBlockPool:
    """Physical paged KV pool + refcounted block accounting for one
    serving replica.

    Owns the per-layer pool arrays (`models.llama.init_kv_pools` layout),
    the stream → block-table map, per-block refcounts, and the prefix
    index. The jitted programs treat the arrays functionally; `update()`
    swaps in each program's returned pools (the programs donate the
    inputs, so the swap is also the memory's lifetime).
    """

    def __init__(self, cfg, num_blocks=None, block_size=None, dtype=None,
                 prefix_sharing=None, scope="kv_pool"):
        from ..models.llama import init_kv_pools
        from ..telemetry import ledger as _ledger
        self.cfg = cfg
        self.num_blocks = int(num_blocks or default_num_blocks())
        self.block_size = int(block_size or default_block_size())
        self._dtype = dtype
        self.pools = init_kv_pools(cfg, self.num_blocks, self.block_size,
                                   dtype=dtype)
        # HBM ledger: the pool arrays are allocated whole up-front — the
        # scope carries the storage bytes; the byte GAUGES carry pressure
        # (blocks in use × bytes/block)
        self.scope = str(scope)
        self._bytes_gauge = ("serve.kv.draft_bytes"
                             if self.scope == "kv_draft"
                             else "serve.kv.bytes")
        self.storage_bytes = _ledger.tree_nbytes(self.pools)
        self.bytes_per_block = (self.storage_bytes // self.num_blocks
                                if self.num_blocks else 0)
        _ledger.account(self.scope, self.storage_bytes)
        # LIFO free-list: a just-freed (cache-warm) block is reused first
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._tables = {}           # stream_id -> [block ids]
        self._refs = {}             # block id -> owner count (tables+index)
        self.prefix_sharing = (prefix_sharing_enabled()
                               if prefix_sharing is None
                               else bool(prefix_sharing))
        self._nodes = {}            # node key -> _PrefixNode
        self._roots = set()         # node keys with parent None
        self._lru_clock = 0
        from ..analysis import lockguard
        self._lock = lockguard.lock("serve.kv_pool")

    # ------------------------------------------------------------- geometry
    def blocks_for(self, n_tokens):
        """Blocks needed to hold an n_tokens context."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    @property
    def free_blocks(self):
        with self._lock:
            return len(self._free)

    @property
    def blocks_in_use(self):
        with self._lock:
            return self.num_blocks - len(self._free)

    @property
    def prefix_blocks(self):
        """Blocks currently pinned by the prefix index."""
        with self._lock:
            return len(self._nodes)

    def _gauge_locked(self):
        return self.num_blocks - len(self._free)

    def _set_block_gauges(self, in_use):
        """blocks_in_use + the per-pool byte gauge, in one place (the
        draft pool reports serve.kv.draft_bytes, the target pool
        serve.kv.bytes — same block math)."""
        _telem.set_gauge("serve.kv.draft_blocks_in_use"
                         if self.scope == "kv_draft"
                         else "serve.kv.blocks_in_use", in_use)
        _telem.set_gauge(self._bytes_gauge, in_use * self.bytes_per_block)

    def _set_prefix_gauges(self, n_blocks):
        """prefix blocks + bytes gauges and the ledger's overlay scope
        (prefix bytes live INSIDE the pool's storage — annotated, not
        summed; see ledger.OVERLAY_SCOPES)."""
        from ..telemetry import ledger as _ledger
        _telem.set_gauge("serve.prefix.blocks", n_blocks)
        _telem.set_gauge("serve.prefix.bytes",
                         n_blocks * self.bytes_per_block)
        _ledger.account("prefix_cache", n_blocks * self.bytes_per_block)

    # -------------------------------------------------------- prefix match
    def _children_of(self, parent_key):
        if parent_key is None:
            return self._roots
        node = self._nodes.get(parent_key)
        return node.children if node is not None else ()

    def _match_locked(self, context, limit):
        """Longest cached prefix of `context`, capped at `limit` tokens.
        Returns (shared block ids, fill_start, cow source block or None).
        Only reads + LRU touches — no refcount changes (commit happens in
        `admit` after the fresh allocation is known to fit)."""
        bs = self.block_size
        matched = []                # fully matched nodes, chain order
        parent = None
        i = 0
        while (i + 1) * bs <= len(context):
            key = (parent, tuple(context[i * bs:(i + 1) * bs]))
            node = self._nodes.get(key)
            if node is None:
                break
            matched.append(node)
            parent = key
            i += 1
        raw = len(matched) * bs
        # sub-block tail: the child whose tokens share the longest prefix
        # with the remainder — its block is the copy-on-write source
        partial_node, partial_len = None, 0
        rest = context[raw:raw + bs]
        if rest:
            for key in self._children_of(parent):
                node = self._nodes[key]
                n = 0
                for a, b in zip(node.tokens, rest):
                    if a != b:
                        break
                    n += 1
                if n > partial_len:
                    partial_node, partial_len = node, n
        fill_start = min(raw + partial_len, limit)
        shared = [n.block for n in matched[:fill_start // bs]]
        cow_src = None
        if fill_start % bs:
            idx = fill_start // bs
            src = matched[idx] if idx < len(matched) else partial_node
            cow_src = src.block
            src.lru = self._lru_clock = self._lru_clock + 1
        for node in matched[:fill_start // bs]:
            node.lru = self._lru_clock = self._lru_clock + 1
        return shared, fill_start, cow_src

    def _evict_locked(self, need, protect=()):
        """Reclaim up to `need` blocks from the prefix index: drop
        least-recently-matched LEAF entries whose block has no other
        owner. An entry some live stream still shares is skipped —
        evicting it would lose the cache without freeing anything — and
        so are `protect`ed blocks (the CURRENT admission's matched
        prefix/CoW source: evicting those would recycle a block into the
        same table twice, the stream clobbering its own shared KV)."""
        protect = set(protect)
        freed = 0
        while freed < need:
            best = None
            for node in self._nodes.values():
                if (node.children or node.block in protect
                        or self._refs.get(node.block, 0) != 1):
                    continue
                if best is None or node.lru < best.lru:
                    best = node
            if best is None:
                break
            self._drop_node_locked(best)
            freed += 1
            _telem.inc("serve.prefix.evictions")
        return freed

    def _drop_node_locked(self, node):
        del self._nodes[node.key]
        (self._roots if node.parent is None
         else self._nodes[node.parent].children).discard(node.key)
        self._unref_locked(node.block)

    def _unref_locked(self, block):
        n = self._refs.get(block, 0) - 1
        if n > 0:
            self._refs[block] = n
            return 0
        self._refs.pop(block, None)
        self._free.append(block)
        return 1

    # ----------------------------------------------------------- allocation
    def alloc(self, stream_id, n_tokens):
        """Grow `stream_id`'s block table to cover `n_tokens` positions.

        All-or-nothing: raises `Overloaded(reason="kv_exhausted")` — having
        reserved NOTHING — when the free-list is short, so a rejected
        admission never strands half a context in the pool."""
        table, _, _ = self.admit(stream_id, n_tokens, context=None)
        return table

    def admit(self, stream_id, n_tokens, context=None):
        """Admission-time reservation: grow the stream's table to cover
        `n_tokens` positions, sharing the longest cached block-aligned
        prefix of `context` (a token list) when prefix sharing is on.

        Returns (table, fill_start, cow): prefill may skip positions
        below `fill_start`; when `cow` is a (src, dst) pair the caller
        must device-copy block src onto the freshly-allocated block dst
        (the divergence block) before relying on positions below
        fill_start in it. All-or-nothing like `alloc` — on `Overloaded`
        nothing is reserved, no refcount moved."""
        need_total = self.blocks_for(n_tokens)
        shared_n = 0
        with self._lock:
            table = self._tables.get(stream_id, [])
            if table:
                # growth of an existing stream never re-matches: its
                # prefix blocks were fixed at first admission
                shared, fill_start, cow_src = [], 0, None
            elif context is not None and self.prefix_sharing:
                _telem.inc("serve.prefix.lookups")
                shared, fill_start, cow_src = self._match_locked(
                    [int(t) for t in context], max(0, len(context) - 1))
            else:
                shared, fill_start, cow_src = [], 0, None
            grow = need_total - len(table) - len(shared)
            if cow_src is not None and grow <= 0:
                cow_src = None      # nothing allocated to copy onto
            protect = set(shared)
            if cow_src is not None:
                protect.add(cow_src)
            if grow > len(self._free):
                # protecting the match never costs capacity: sharing s
                # blocks shrinks the demand by exactly the s blocks an
                # unshared admission would have had to evict, so if this
                # still comes up short the pool is GENUINELY full and
                # Overloaded (backpressure) is the right verdict
                self._evict_locked(grow - len(self._free), protect=protect)
            if max(grow, 0) > len(self._free):
                # reserve NOTHING on failure — not even an empty table
                # entry: rejected stream ids are uuids that never return,
                # so a leftover entry would leak one dict slot per shed
                free = len(self._free)
                _telem.inc("serve.kv.exhausted")
                from ..telemetry import ledger as _ledger
                brk = _ledger.breakdown()
                raise Overloaded(
                    "KV pool exhausted: stream %r needs %d more block(s) "
                    "(%d tokens) but only %d of %d are free%s"
                    % (stream_id, grow, n_tokens, free, self.num_blocks,
                       ("; HBM ledger: " + brk) if brk else ""),
                    reason="kv_exhausted", kv_free_blocks=free,
                    kv_needed_blocks=grow,
                    ledger_breakdown=_ledger.scopes() or None)
            if grow <= 0 and not shared:
                return list(table), 0, None
            for b in shared:
                self._refs[b] = self._refs.get(b, 0) + 1
            fresh = [self._free.pop() for _ in range(max(grow, 0))]
            for b in fresh:
                self._refs[b] = 1
            table = table + shared + fresh
            self._tables[stream_id] = table
            cow = (cow_src, table[len(shared)]) if cow_src is not None \
                else None
            shared_n = len(shared)
            in_use = self._gauge_locked()
        _telem.inc("serve.kv.allocs")
        if shared_n or cow is not None:
            # a CoW-only match (divergence inside the first block) still
            # reused cached KV — it is a hit, not a miss
            _telem.inc("serve.prefix.hits")
        if shared_n:
            _telem.inc("serve.prefix.blocks_shared", shared_n)
        if cow is not None:
            _telem.inc("serve.prefix.cow")
        self._set_block_gauges(in_use)
        return list(table), fill_start, cow

    def free(self, stream_id):
        """Drop the stream's references; blocks with no other owner (a
        sharing sibling or the prefix index) return to the free-list
        (idempotent). Returns the number of blocks actually freed."""
        with self._lock:
            table = self._tables.pop(stream_id, None)
            if not table:
                return 0
            freed = sum(self._unref_locked(b) for b in table)
            in_use = self._gauge_locked()
        if freed:
            _telem.inc("serve.kv.freed_blocks", freed)
        self._set_block_gauges(in_use)
        return freed

    # -------------------------------------------------------- prefix index
    def register_prefix(self, stream_id, tokens):
        """Hash-cons the stream's FULL blocks covering `tokens` (its
        prompt) into the prefix index, once its prefill has written them.
        Already-cached chains are left alone (the stream either shared
        them at admission or raced a twin — either way the index keeps
        ONE block per distinct chain); new entries pin the stream's own
        block with an index reference so the prefix outlives the
        stream."""
        if not self.prefix_sharing:
            return 0
        bs = self.block_size
        tokens = [int(t) for t in tokens]
        inserted = 0
        with self._lock:
            table = self._tables.get(stream_id, ())
            parent = None
            for i in range(min(len(tokens) // bs, len(table))):
                key = (parent, tuple(tokens[i * bs:(i + 1) * bs]))
                node = self._nodes.get(key)
                if node is None:
                    node = _PrefixNode(key, parent, key[1], table[i],
                                       self._lru_clock)
                    self._nodes[key] = node
                    (self._roots if parent is None
                     else self._nodes[parent].children).add(key)
                    self._refs[table[i]] = self._refs.get(table[i], 0) + 1
                    inserted += 1
                parent = key
            n_blocks = len(self._nodes)
        if inserted:
            _telem.inc("serve.prefix.inserted", inserted)
        self._set_prefix_gauges(n_blocks)
        return inserted

    def clear_prefix_cache(self):
        """Drop every cached prefix (and its index references). Recovery
        calls this after the pool storage was re-materialized: the arrays
        are fresh zeros, so every cached block's CONTENT is gone and a
        future match would serve garbage KV."""
        with self._lock:
            freed = sum(self._unref_locked(n.block)
                        for n in self._nodes.values())
            self._nodes.clear()
            self._roots.clear()
            in_use = self._gauge_locked()
        if freed:
            _telem.inc("serve.kv.freed_blocks", freed)
        self._set_prefix_gauges(0)
        self._set_block_gauges(in_use)
        return freed

    def table(self, stream_id, width):
        """The stream's block table as a width-`width` int32 array, padded
        with the `num_blocks` sentinel (dropped writes / masked reads).
        Truncates past `width`: a prefill window's table only names the
        blocks its positions can touch, even when the stream reserved its
        worst-case context up front."""
        with self._lock:
            blocks = list(self._tables.get(stream_id, ()))[:width]
        out = np.full(width, self.num_blocks, np.int32)
        out[:len(blocks)] = blocks
        return out

    def owned_blocks(self, stream_id):
        with self._lock:
            return list(self._tables.get(stream_id, ()))

    # -------------------------------------------------------------- storage
    def update(self, new_pools):
        """Adopt the pools a prefill/decode program returned (the program
        donated the previous arrays)."""
        self.pools = new_pools

    def reconcile(self):
        """Rebuild refcounts and the free-list as the exact complement of
        every live owner (stream tables + prefix index). Recovery calls
        this because an async fault (the watchdog's StallError lands at
        any bytecode) can tear alloc/free mid-flight: blocks popped from
        the free-list but not yet committed to a table — or dropped from
        a table but not yet returned — are in NEITHER structure and would
        otherwise leak forever, shrinking effective pool capacity with
        every stall; likewise a torn refcount would double-free a shared
        prefix block under a live sibling. Returns the net number of
        blocks recovered (0 when nothing was torn)."""
        with self._lock:
            refs = {}
            for table in self._tables.values():
                for b in table:
                    refs[b] = refs.get(b, 0) + 1
            for node in self._nodes.values():
                refs[node.block] = refs.get(node.block, 0) + 1
            before = len(self._free)
            self._refs = refs
            self._free = [b for b in range(self.num_blocks - 1, -1, -1)
                          if b not in refs]
            recovered = len(self._free) - before
            in_use = self._gauge_locked()
        if recovered:
            _telem.inc("serve.kv.reconciled_blocks", recovered)
            self._set_block_gauges(in_use)
        return recovered

    def ensure_storage(self):
        """Heal donation wreckage after a fault: an async StallError can
        land between a donating program call and `update`, leaving
        `pools` pointing at deleted buffers. Recovery requeues every
        stream for re-prefill, so the CONTENT is worthless anyway — the
        arrays just have to be alive again. Returns True when the pools
        were re-materialized (the caller must then `clear_prefix_cache`:
        cached prefixes point into the zeroed arrays)."""
        import jax
        from ..models.llama import init_kv_pools
        leaves = jax.tree_util.tree_leaves(self.pools)
        if not any(isinstance(x, jax.Array) and x.is_deleted()
                   for x in leaves):
            return False
        self.pools = init_kv_pools(self.cfg, self.num_blocks,
                                   self.block_size, dtype=self._dtype)
        from ..telemetry import ledger as _ledger
        self.storage_bytes = _ledger.tree_nbytes(self.pools)
        _ledger.account(self.scope, self.storage_bytes)
        _telem.inc("serve.kv.storage_resets")
        return True
