"""Replica-group supervision: N serving replicas over one shared queue.

`ResilientRunner` semantics applied to serving: each replica is an
`InferenceServer` on its own thread with its own KV pool and compiled
programs, all admitting from ONE shared `RequestQueue`. A replica that
takes a retriable fault drains in place (its in-flight streams re-enter
the shared queue and resume by re-prefill — `InferenceServer._recover`);
a replica that spends its restart budget **dies**, and because the drain
happens before the death, its streams are already queued for the
survivors — a killed replica costs requeues, never tokens. The group is
healthy while any replica lives.

Telemetry: ``serve.replica_deaths`` counter (from the server),
``serve.replicas_alive`` gauge, per-replica flight-recorder events.
"""
from __future__ import annotations

import logging
import threading

from .. import telemetry as _telem
from ..telemetry import flight as _flight
from .errors import ServeError
from .scheduler import InferenceServer, RequestQueue

__all__ = ["ReplicaGroup"]

_LOG = logging.getLogger("mxnet_tpu.serve")


class ReplicaGroup:
    """Usage::

        group = mx.serve.ReplicaGroup(params, cfg, replicas=2)
        group.warmup().start()
        handles = [group.submit(r) for r in requests]
        for h in handles:
            h.result(timeout=60)
        group.stop()
    """

    def __init__(self, params, cfg, replicas=2, queue_cap=None,
                 **server_kwargs):
        if replicas < 1:
            raise ValueError("serve: a replica group needs >= 1 replica")
        self.queue = RequestQueue(queue_cap)
        self.servers = [
            InferenceServer(params, cfg, queue=self.queue,
                            name="replica%d" % i, **server_kwargs)
            for i in range(int(replicas))]
        self._threads = []
        self._stop = threading.Event()

    def warmup(self):
        for server in self.servers:
            server.warmup()
        return self

    # ---------------------------------------------------------------- life
    def _loop(self, server):
        try:
            server.run(stop=self._stop)
        except Exception as exc:  # noqa: BLE001 — a dead replica must not
            # take the group down; its streams were requeued by _recover
            server.dead = True
            _LOG.warning("serve: %s died (%s: %s); %d replica(s) remain",
                         server.name, type(exc).__name__, exc,
                         self.alive_replicas)
            # flight-ring event next to the serve_recover that drained the
            # streams: the post-mortem reads death + survivor count in one
            # place
            _flight.note_event(
                "serve_replica_death", "%s: %s (%d alive)"
                % (server.name, type(exc).__name__, self.alive_replicas))
        finally:
            _telem.set_gauge("serve.replicas_alive", self.alive_replicas)

    def start(self):
        self._stop.clear()
        self._threads = [
            threading.Thread(target=self._loop, args=(server,),
                             name="mxnet_tpu_%s" % server.name, daemon=True)
            for server in self.servers]
        for t in self._threads:
            t.start()
        _telem.set_gauge("serve.replicas_alive", self.alive_replicas)
        return self

    def stop(self, timeout=30.0):
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads = []

    @property
    def alive_replicas(self):
        return sum(1 for s in self.servers if not s.dead)

    # ------------------------------------------------------------- traffic
    def submit(self, request):
        """Admit through any live replica (admission state — queue cap,
        pool geometry — is identical across the group)."""
        for server in self.servers:
            if not server.dead:
                return server.submit(request)
        raise ServeError("serve: every replica in the group is dead")

    def drain(self, timeout=60.0):
        """Block until the shared queue and every live replica's batch are
        empty (best-effort; returns False on timeout)."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            idle = len(self.queue) == 0 and all(
                s.dead or (s._admitting is None
                           and all(slot is None for slot in s._slots))
                for s in self.servers)
            if idle:
                return True
            time.sleep(0.01)
        return False
