"""Structured serving errors: load-shedding and deadline misses.

A serving runtime under pressure must fail *requests*, never the process:
an exhausted KV pool or a full queue answers with a structured `Overloaded`
(carrying enough state for the client to back off intelligently) instead of
marching into an OOM, and a request that cannot meet its deadline is shed
with `DeadlineExceeded` (carrying whatever tokens it did produce) instead
of burning decode slots on an answer nobody is waiting for.

Both are `ResilienceError`s but deliberately NOT `RetriableError`s: they
are verdicts about *this request under this load*, not transient transport
faults — the in-runtime recovery machinery (retry policies, drain/requeue)
must never spin on them.
"""
from __future__ import annotations

from ..resilience.errors import ResilienceError

__all__ = ["ServeError", "Overloaded", "DeadlineExceeded"]


class ServeError(ResilienceError):
    """Base class of every error raised by mxnet_tpu.serve."""


class Overloaded(ServeError):
    """Graceful load-shed: the runtime cannot admit this request right now.

    reason: ``queue_full`` (admission queue at capacity), ``kv_exhausted``
    (the paged KV pool cannot hold the request's worst-case context), or
    ``too_large`` (the request can NEVER fit — prompt + budget exceeds the
    pool or the bucket table; retrying is pointless).
    """

    def __init__(self, message, reason=None, queue_depth=None,
                 kv_free_blocks=None, kv_needed_blocks=None,
                 retry_after_s=None, ledger_breakdown=None):
        super().__init__(message)
        self.reason = reason
        self.queue_depth = queue_depth
        self.kv_free_blocks = kv_free_blocks
        self.kv_needed_blocks = kv_needed_blocks
        self.retry_after_s = retry_after_s
        # {scope: bytes} from the HBM ledger at shed time (kv_exhausted
        # verdicts): WHICH subsystem's bytes crowded the pool out, not
        # just that it was full. None when the ledger is disabled.
        self.ledger_breakdown = (dict(ledger_breakdown)
                                 if ledger_breakdown else None)


class DeadlineExceeded(ServeError):
    """The request's deadline passed — in the queue (no tokens) or
    mid-stream (`tokens` carries the partial output).

    `request_trace` carries the request's own timeline (the
    `RequestTrace` payload: queue-wait vs prefill vs decode vs recovery,
    across every replica that held it) — a shed request arrives at the
    client with its post-mortem attached."""

    def __init__(self, message, tokens=None, request_trace=None):
        super().__init__(message)
        self.tokens = list(tokens or [])
        self.request_trace = request_trace
