"""AOT-compiled serving executables: chunk prefill, decode, draft, verify.

The reference's answer to varying sequence lengths was BucketingModule —
one symbolic executor per bucket, picked at dispatch time. The first cut of
this module kept that shape (one prefill executable per power-of-two
context bucket, batch-1); serving v2 replaces it with a **fixed-shape
multi-stream chunk program**: every prefill is a (rows × chunk) window of
prompt tokens scattered into the paged pool at their absolute positions,
so one executable serves every context length, burst arrivals prefill
TOGETHER instead of serializing TTFT behind batch-1 programs, and prompt
work interleaves with decode steps under a per-step token budget (the
scheduler's job). The same chunk math, at shape (max_batch × spec_k),
is the speculative-decoding **verify** program; a small draft model rides
identical plumbing (chunk prefill to mirror the prompt, an unrolled
greedy draft-k program). Every executable is lowered and compiled at
**warm-up** and restored from the persistent AOT cache
(``MXNET_TPU_AOT_CACHE``) when a previous process already built it — so
admission can never trigger a mid-traffic retrace and a warmed fleet
cold-starts at zero fresh compiles. Compiles route through
``telemetry.note_compile`` (the acceptance evidence: the compile ring must
not grow after warm-up), and a post-warm-up signature miss is treated
exactly like a CachedOp retrace: counted (``serve.retrace``), explained,
and routed through ``analysis.guard.on_retrace``.

Sampling happens inside the chunk/decode programs (`serve.sampling`):
per-slot temperature/top-k/top-p vectors and a per-stream seed keyed by
position, so greedy streams stay exactly argmax (one int32 per stream
crosses the device boundary, not a vocab row) and sampled streams replay
the same draws after kill-recovery. Speculative decoding stays
greedy-verify: the draft-k / verify-k pair multiplies tokens/s exactly
where decode is HBM-bandwidth-bound, with byte-identical output to the
non-speculative greedy path as the correctness bar.

The executable inventory per replica (all fixed-shape):

* ``chunk``        (P, C) multi-stream prefill window + sampled next token
* ``decode``       (B,) one token per active slot + sampling
* ``copy``         one-block device copy (the prefix-sharing CoW)
* ``draft_chunk``  (P, C) draft-model prompt mirror          [spec only]
* ``draft_k``      (B, k) unrolled greedy draft              [spec only]
* ``verify``       (B, k+1) target greedy over drafted tokens [spec only]
* ``draft_copy``   CoW for the draft pool                    [spec only]
"""
from __future__ import annotations

import os
import time

import jax
import numpy as np

from .. import telemetry as _telem
from .sampling import sample_tokens

__all__ = ["ServePrograms", "default_chunk_size", "default_prefill_rows",
           "default_spec_k"]


def default_chunk_size():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_CHUNK", "16")))
    except (TypeError, ValueError):
        return 16


def default_prefill_rows():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_PREFILL_ROWS",
                                         "4")))
    except (TypeError, ValueError):
        return 4


def default_spec_k():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_SPEC_K", "4")))
    except (TypeError, ValueError):
        return 4


class ServePrograms:
    """The compiled half of a serving replica: params + pool geometry in,
    token ids out. The scheduler owns WHAT runs when; this owns the
    executables and the no-retrace contract."""

    def __init__(self, params, cfg, pool, max_batch, max_context,
                 chunk_size=None, prefill_rows=None, draft_params=None,
                 draft_cfg=None, draft_pool=None, spec_k=None):
        from ..models.llama import (llama_chunk_paged, llama_decode_paged,
                                    llama_draft_loop)
        self.params = params
        self.cfg = cfg
        self.pool = pool
        self.max_batch = int(max_batch)
        bs = pool.block_size
        self.max_context = min(int(max_context), cfg.max_seq_len)
        self.blocks_per_stream = -(-self.max_context // bs)
        self.chunk_size = int(chunk_size or default_chunk_size())
        self.prefill_rows = int(prefill_rows or default_prefill_rows())
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.draft_pool = draft_pool
        self.spec = draft_params is not None
        self.spec_k = int(spec_k or default_spec_k()) if self.spec else 0
        if self.spec and (draft_cfg is None or draft_pool is None):
            raise ValueError("serve: a draft model needs draft_cfg and a "
                             "mirrored draft KV pool")
        jnp = jax.numpy

        def _chunk(params, pools, tokens, positions, tables, seeds,
                   sample_pos, temps, top_k, top_p):
            logits, pools = llama_chunk_paged(
                params, pools, tokens, positions, tables, cfg, bs,
                logits_at="last")
            tok = sample_tokens(logits, seeds, sample_pos, temps,
                                top_k, top_p)
            return tok, pools

        def _decode(params, pools, tokens, positions, tables, seeds,
                    temps, top_k, top_p):
            logits, pools = llama_decode_paged(
                params, pools, tokens, positions, tables, cfg, bs)
            tok = sample_tokens(logits, seeds, positions + 1, temps,
                                top_k, top_p)
            return tok, pools

        def _copy(pools, src, dst):
            # the CoW primitive: block dst becomes a copy of block src in
            # every layer's k and v pool
            return jax.tree_util.tree_map(
                lambda a: a.at[dst].set(a[src]), pools)

        self._chunk_jit = jax.jit(_chunk, donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode, donate_argnums=(1,))
        self._copy_jit = jax.jit(_copy, donate_argnums=(0,))
        self._exec = {}
        self._warm = False

        if self.spec:
            dcfg = draft_cfg

            def _draft_chunk(dparams, dpools, tokens, positions, tables):
                _, dpools = llama_chunk_paged(
                    dparams, dpools, tokens, positions, tables, dcfg, bs,
                    logits_at="last")
                return dpools

            def _draft_k(dparams, dpools, tokens, positions, tables):
                return llama_draft_loop(dparams, dpools, tokens, positions,
                                        tables, dcfg, bs, self.spec_k)

            def _verify(params, pools, tokens, positions, tables):
                logits, pools = llama_chunk_paged(
                    params, pools, tokens, positions, tables, cfg, bs,
                    logits_at="all")
                return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                        pools)

            self._draft_chunk_jit = jax.jit(_draft_chunk,
                                            donate_argnums=(1,))
            self._draft_k_jit = jax.jit(_draft_k, donate_argnums=(1,))
            self._verify_jit = jax.jit(_verify, donate_argnums=(1,))

    # -------------------------------------------------------------- warmup
    def _pool_avals(self, pool):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), pool.pools)

    def _cache_key(self, kind, params, pool, **extra):
        """AOT-cache signature for one serve executable: model geometry +
        pool geometry + param avals (+ versions, folded in by cache_key).
        Param VALUES stay out — executables are value-independent."""
        import dataclasses

        from ..compiler.cache import avals_sig, cache_key
        cfg = self.draft_cfg if kind.startswith("draft") else self.cfg
        cfg = (dataclasses.asdict(cfg)
               if dataclasses.is_dataclass(cfg) else repr(cfg))
        if isinstance(cfg, dict) and "dtype" in cfg:
            # canonicalize semantically: jnp.float32 and np.float32 repr
            # differently but compile the same executable — a manifest
            # pre-bake and a live replica must land on ONE key
            try:
                cfg["dtype"] = str(jax.numpy.dtype(cfg["dtype"]))
            except TypeError:
                cfg["dtype"] = repr(cfg["dtype"])
        return cache_key(
            kind="serve.%s" % kind, cfg=cfg,
            block_size=self.pool.block_size, max_batch=self.max_batch,
            blocks_per_stream=self.blocks_per_stream,
            chunk=self.chunk_size, rows=self.prefill_rows,
            spec_k=self.spec_k,
            params=avals_sig(params), pools=avals_sig(pool.pools),
            **extra)

    def _compile_or_restore(self, name, jitted, args):
        """One serve executable: AOT-cache hit restores the serialized
        binary (zero fresh compiles — the fleet cold-start win); miss
        lowers+compiles and stores it for the next replica. Either way the
        compile ring records the program, tagged cached vs fresh."""
        from ..compiler.cache import load_or_compile
        label = "serve.%s" % name
        t0 = time.perf_counter()
        ex, restored = load_or_compile(
            self._keys[name], lambda: jitted.lower(*args), label,
            meta={"kind": name})
        if not restored:
            _telem.inc("serve.compile")
            _telem.observe("serve.compile_ms",
                           (time.perf_counter() - t0) * 1e3)
            _telem.note_compile(label)
        self._exec[name] = ex
        return ex

    def _program_args(self, name):
        """(jitted fn, lowering avals) per executable name."""
        i32, f32, u32 = (jax.numpy.int32, jax.numpy.float32,
                         jax.numpy.uint32)

        def s(shape, dt=i32):
            return jax.ShapeDtypeStruct(shape, dt)

        P, C = self.prefill_rows, self.chunk_size
        B, k, nb = self.max_batch, self.spec_k, self.blocks_per_stream
        pool_av = self._pool_avals(self.pool)
        if name == "chunk":
            return self._chunk_jit, (
                self.params, pool_av, s((P, C)), s((P, C)), s((P, nb)),
                s((P,), u32), s((P,)), s((P,), f32), s((P,)), s((P,), f32))
        if name == "decode":
            return self._decode_jit, (
                self.params, pool_av, s((B,)), s((B,)), s((B, nb)),
                s((B,), u32), s((B,), f32), s((B,)), s((B,), f32))
        if name == "copy":
            return self._copy_jit, (pool_av, s(()), s(()))
        draft_av = self._pool_avals(self.draft_pool)
        if name == "draft_chunk":
            return self._draft_chunk_jit, (
                self.draft_params, draft_av, s((P, C)), s((P, C)),
                s((P, nb)))
        if name == "draft_k":
            return self._draft_k_jit, (
                self.draft_params, draft_av, s((B,)), s((B,)), s((B, nb)))
        if name == "verify":
            # width k+1: [last accepted token, d1..dk] — verifying all k
            # drafts needs the target's answer AFTER each of them
            return self._verify_jit, (
                self.params, pool_av, s((B, k + 1)), s((B, k + 1)),
                s((B, nb)))
        if name == "draft_copy":
            return self._copy_jit, (draft_av, s(()), s(()))
        raise KeyError(name)

    @property
    def program_names(self):
        names = ["chunk", "decode", "copy"]
        if self.spec:
            names += ["draft_chunk", "draft_k", "verify", "draft_copy"]
        return names

    def _key_for(self, name):
        """One AOT-cache key per executable name (draft programs key on
        the draft model/pool, everything else on the target's)."""
        params = (self.draft_params if name.startswith("draft")
                  else self.params)
        pool = (self.draft_pool if name.startswith("draft")
                else self.pool)
        return self._cache_key(name, params, pool)

    def warmup(self):
        """Compile every executable a request could route to. After this,
        steady-state traffic never compiles (the acceptance bar)."""
        self._keys = {name: self._key_for(name)
                      for name in self.program_names}
        with _telem.span("serve.warmup", "serve"):
            for name in self.program_names:
                if name not in self._exec:
                    jitted, args = self._program_args(name)
                    self._compile_or_restore(name, jitted, args)
        self._warm = True

    def _on_miss(self, kind, reason):
        """A post-warm-up signature miss IS a retrace: count it, explain
        it, and give the trace guard its veto."""
        if not self._warm:
            return
        _telem.inc("serve.retrace")
        _telem.note_compile("serve.%s(retrace)" % kind)
        from ..analysis import guard as _guard
        if _guard.ACTIVE:
            _guard.on_retrace("serve.%s" % kind, len(self._exec) + 1,
                              reason)

    def _run(self, name):
        ex = self._exec.get(name)
        if ex is None:
            self._on_miss(name, "executable %r missing at dispatch "
                          "(warmed: %s)" % (name,
                                            ",".join(self._exec) or "none"))
            if not hasattr(self, "_keys"):
                self._keys = {}
            self._keys[name] = self._key_for(name)
            jitted, args = self._program_args(name)
            ex = self._compile_or_restore(name, jitted, args)
        return ex

    # ------------------------------------------------------------- execute
    def chunk_prefill(self, tokens, positions, tables, seeds, sample_pos,
                      temps, top_k, top_p):
        """One multi-stream prefill window: rows of (chunk_size,) prompt
        tokens at absolute positions (−1 = pad). Returns the sampled
        next-token per row (meaningful only for rows that completed their
        stream's fill — the scheduler knows which)."""
        ex = self._run("chunk")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        tok, pools = ex(self.params, self.pool.pools,
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(tables, np.int32),
                        np.asarray(seeds, np.uint32),
                        np.asarray(sample_pos, np.int32),
                        np.asarray(temps, np.float32),
                        np.asarray(top_k, np.int32),
                        np.asarray(top_p, np.float32))
        self.pool.update(pools)
        # one span per chunk window (cat `serve`): in the chrome dump the
        # prefill windows line up under the serve.step row, and the
        # attribution pass sees the serving host timeline
        _telem.record_span(
            "serve.prefill[%dx%d]" % (self.prefill_rows, self.chunk_size),
            "serve", ts, time.perf_counter() - t0)
        return np.asarray(tok)

    def decode(self, tokens, positions, tables, seeds, temps, top_k,
               top_p):
        """One decode step over the fixed-size batch. tokens/positions
        (max_batch,) int32 (position -1 = inactive slot), tables
        (max_batch, blocks_per_stream) int32, sampling vectors
        row-aligned. Returns the next token id per slot as a numpy
        (max_batch,) array."""
        ex = self._run("decode")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        out, pools = ex(self.params, self.pool.pools,
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(tables, np.int32),
                        np.asarray(seeds, np.uint32),
                        np.asarray(temps, np.float32),
                        np.asarray(top_k, np.int32),
                        np.asarray(top_p, np.float32))
        self.pool.update(pools)
        _telem.record_span("serve.decode", "serve", ts,
                           time.perf_counter() - t0)
        return np.asarray(out)

    def copy_block(self, src, dst):
        """Device-copy pool block src -> dst (the prefix-sharing CoW)."""
        ex = self._run("copy")
        self.pool.update(ex(self.pool.pools, np.int32(src), np.int32(dst)))

    # ------------------------------------------------------- spec decoding
    def draft_prefill(self, tokens, positions, tables):
        """Mirror a prefill window through the draft model (spec decoding
        needs the draft's KV for the whole context)."""
        ex = self._run("draft_chunk")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        self.draft_pool.update(
            ex(self.draft_params, self.draft_pool.pools,
               np.asarray(tokens, np.int32), np.asarray(positions, np.int32),
               np.asarray(tables, np.int32)))
        _telem.record_span("serve.draft_prefill", "serve", ts,
                           time.perf_counter() - t0)

    def draft_k(self, tokens, positions, tables):
        """spec_k greedy draft tokens per slot in ONE program: (B, k)."""
        ex = self._run("draft_k")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        out, pools = ex(self.draft_params, self.draft_pool.pools,
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(tables, np.int32))
        self.draft_pool.update(pools)
        _telem.record_span("serve.draft", "serve", ts,
                           time.perf_counter() - t0)
        return np.asarray(out)

    def verify(self, tokens, positions, tables):
        """Target-model greedy tokens at every drafted position, one
        chunk-shaped pass: (B, k+1) in, (B, k+1) out."""
        ex = self._run("verify")
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        out, pools = ex(self.params, self.pool.pools,
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(tables, np.int32))
        self.pool.update(pools)
        _telem.record_span("serve.verify", "serve", ts,
                           time.perf_counter() - t0)
        return np.asarray(out)

    def draft_copy_block(self, src, dst):
        ex = self._run("draft_copy")
        self.draft_pool.update(
            ex(self.draft_pool.pools, np.int32(src), np.int32(dst)))
