"""AOT-compiled prefill/decode executables per bucketed signature.

The reference's answer to varying sequence lengths was BucketingModule —
one symbolic executor per bucket, picked at dispatch time. Relay (PAPERS.md)
sharpened that into ahead-of-time compilation per input signature. This
module is the serving version of both: every program a request could need
is lowered and compiled at **warm-up** — one prefill executable per
bucketed context length (right-padded, length-masked) and ONE decode
executable for the whole replica (batch and block-table dims fixed at
``max_batch`` × ``blocks_per_stream``; streams join/leave between steps by
flipping slots active/inactive, never by changing a shape) — so admission
can never trigger a mid-traffic retrace. Compiles route through
``telemetry.note_compile`` (the acceptance evidence: the compile ring must
not grow after warm-up), and a post-warm-up signature miss is treated
exactly like a CachedOp retrace: counted (``serve.retrace``), explained,
and routed through ``analysis.guard.on_retrace`` so the trace guard's
retrace limit covers the serving path too.

Sampling is greedy (argmax inside the program — one int32 per stream
crosses the device boundary, not a vocab row). Greedy is also what makes
kill-mid-stream recovery *byte-identical*: re-prefilling an interrupted
stream's prompt + already-emitted tokens rebuilds the same KV state, so the
resumed decode continues the exact token trajectory.
"""
from __future__ import annotations

import functools
import time

import jax
import numpy as np

from .. import telemetry as _telem

__all__ = ["ServePrograms", "default_buckets"]


def default_buckets(block_size, max_context):
    """Power-of-two context buckets, block-aligned, covering max_context."""
    out = []
    b = max(int(block_size), 8)
    while b < max_context:
        out.append(b)
        b *= 2
    out.append(-(-int(max_context) // block_size) * block_size)
    return tuple(sorted(set(out)))


class ServePrograms:
    """The compiled half of a serving replica: params + pool geometry in,
    token ids out. The scheduler owns WHAT runs when; this owns the
    executables and the no-retrace contract."""

    def __init__(self, params, cfg, pool, max_batch, max_context,
                 buckets=None):
        from ..models.llama import llama_decode_paged, llama_prefill_paged
        self.params = params
        self.cfg = cfg
        self.pool = pool
        self.max_batch = int(max_batch)
        bs = pool.block_size
        self.max_context = min(int(max_context), cfg.max_seq_len)
        self.blocks_per_stream = -(-self.max_context // bs)
        self.buckets = tuple(b for b in (buckets
                                         or default_buckets(
                                             bs, self.max_context))
                             if b % bs == 0)
        if not self.buckets:
            raise ValueError(
                "serve: no valid prefill buckets (buckets must be "
                "multiples of the KV block size %d)" % bs)

        def _prefill(params, pools, tokens, length, table):
            logits, pools = llama_prefill_paged(
                params, pools, tokens, length, table, cfg, bs)
            return jax.numpy.argmax(logits).astype(jax.numpy.int32), pools

        def _decode(params, pools, tokens, positions, tables):
            logits, pools = llama_decode_paged(
                params, pools, tokens, positions, tables, cfg, bs)
            return (jax.numpy.argmax(logits, axis=-1).astype(
                jax.numpy.int32), pools)

        self._prefill_jit = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_jit = jax.jit(_decode, donate_argnums=(1,))
        self._prefill_exec = {}
        self._decode_exec = None
        self._warm = False

    # ------------------------------------------------------------- buckets
    def bucket_for(self, n_tokens):
        """Smallest warmed bucket holding n_tokens, or None (too large)."""
        for b in self.buckets:
            if n_tokens <= b:
                return b
        return None

    # -------------------------------------------------------------- warmup
    def _pool_avals(self):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.pool.pools)

    def _cache_key(self, kind, **extra):
        """AOT-cache signature for one serve executable: model geometry +
        pool geometry + param avals (+ versions, folded in by cache_key).
        Param VALUES stay out — executables are value-independent."""
        import dataclasses

        from ..compiler.cache import avals_sig, cache_key
        cfg = (dataclasses.asdict(self.cfg)
               if dataclasses.is_dataclass(self.cfg) else repr(self.cfg))
        return cache_key(
            kind="serve.%s" % kind, cfg=cfg,
            block_size=self.pool.block_size, max_batch=self.max_batch,
            blocks_per_stream=self.blocks_per_stream,
            params=avals_sig(self.params), pools=avals_sig(self.pool.pools),
            **extra)

    def _compile_or_restore(self, jitted, avals, kind, key, name):
        """One serve executable: AOT-cache hit restores the serialized
        binary (zero fresh compiles — the fleet cold-start win); miss
        lowers+compiles and stores it for the next replica. Either way the
        compile ring records the program, tagged cached vs fresh."""
        from ..compiler.cache import load_or_compile
        label = "serve.%s" % name
        t0 = time.perf_counter()
        ex, restored = load_or_compile(
            key, lambda: jitted.lower(self.params, self._pool_avals(),
                                      *avals),
            label, meta={"kind": kind})
        if not restored:
            _telem.inc("serve.compile")
            _telem.observe("serve.compile_ms",
                           (time.perf_counter() - t0) * 1e3)
            _telem.note_compile(label)
        return ex

    def _compile_prefill(self, bucket):
        i32 = jax.numpy.int32
        ex = self._compile_or_restore(
            self._prefill_jit,
            (jax.ShapeDtypeStruct((bucket,), i32),
             jax.ShapeDtypeStruct((), i32),
             jax.ShapeDtypeStruct((bucket // self.pool.block_size,), i32)),
            "prefill", self._cache_key("prefill", bucket=bucket),
            "prefill[S=%d]" % bucket)
        self._prefill_exec[bucket] = ex
        return ex

    def _compile_decode(self):
        i32 = jax.numpy.int32
        ex = self._compile_or_restore(
            self._decode_jit,
            (jax.ShapeDtypeStruct((self.max_batch,), i32),
             jax.ShapeDtypeStruct((self.max_batch,), i32),
             jax.ShapeDtypeStruct((self.max_batch, self.blocks_per_stream),
                                  i32)),
            "decode", self._cache_key("decode"),
            "decode[B=%d]" % self.max_batch)
        self._decode_exec = ex
        return ex

    def warmup(self):
        """Compile every executable a request could route to. After this,
        steady-state traffic never compiles (the acceptance bar)."""
        with _telem.span("serve.warmup", "serve"):
            for bucket in self.buckets:
                if bucket not in self._prefill_exec:
                    self._compile_prefill(bucket)
            if self._decode_exec is None:
                self._compile_decode()
        self._warm = True

    def _on_miss(self, kind, reason):
        """A post-warm-up signature miss IS a retrace: count it, explain
        it, and give the trace guard its veto."""
        if not self._warm:
            return
        _telem.inc("serve.retrace")
        _telem.note_compile("serve.%s(retrace)" % kind)
        from ..analysis import guard as _guard
        if _guard.ACTIVE:
            n = len(self._prefill_exec) + (1 if self._decode_exec else 0)
            _guard.on_retrace("serve.%s" % kind, n + 1, reason)

    # ------------------------------------------------------------- execute
    def prefill(self, tokens, table):
        """Run the bucketed prefill for a context of `tokens` (list/array
        of ints). `table` is the stream's padded-to-bucket block table.
        Returns the next token id (int)."""
        n = len(tokens)
        bucket = self.bucket_for(n)
        if bucket is None:
            raise ValueError(
                "serve: context of %d tokens exceeds the largest bucket "
                "(%d) — admission should have shed this request"
                % (n, self.buckets[-1]))
        ex = self._prefill_exec.get(bucket)
        if ex is None:
            self._on_miss("prefill", "unwarmed bucket S=%d (warmed: %s)"
                          % (bucket, ",".join(map(str, self._prefill_exec))
                             or "none"))
            ex = self._compile_prefill(bucket)
        padded = np.zeros(bucket, np.int32)
        padded[:n] = tokens
        tbl = np.asarray(table, np.int32)[:bucket // self.pool.block_size]
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        tok, pools = ex(self.params, self.pool.pools, padded,
                        np.int32(n), tbl)
        self.pool.update(pools)
        # one span per prefill dispatch (cat `serve`): in the chrome dump
        # the bucketed prefills line up under the serve.step row, and the
        # attribution pass sees the serving host timeline
        _telem.record_span("serve.prefill[S=%d]" % bucket, "serve", ts,
                           time.perf_counter() - t0)
        return int(tok)

    def decode(self, tokens, positions, tables):
        """One decode step over the fixed-size batch. tokens/positions
        (max_batch,) int32 (position -1 = inactive slot), tables
        (max_batch, blocks_per_stream) int32. Returns the next token id
        per slot as a numpy (max_batch,) array."""
        ex = self._decode_exec
        if ex is None:
            self._on_miss("decode", "decode executable missing at dispatch")
            ex = self._compile_decode()
        ts = _telem.span_clock()
        t0 = time.perf_counter()
        out, pools = ex(self.params, self.pool.pools,
                        np.asarray(tokens, np.int32),
                        np.asarray(positions, np.int32),
                        np.asarray(tables, np.int32))
        self.pool.update(pools)
        _telem.record_span("serve.decode", "serve", ts,
                           time.perf_counter() - t0)
        return np.asarray(out)
