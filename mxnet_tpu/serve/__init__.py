"""`mx.serve` — fault-tolerant continuous-batching inference.

Training got the first seven PRs; this subsystem spends that
infrastructure on the north star's other half: serving. One replica is an
`InferenceServer` — a continuous-batching scheduler (requests join/leave
the running batch between decode steps) over a **refcounted paged
KV-cache allocator** (`KVBlockPool`: fixed-size blocks + free-list +
hash-consed shared-prefix index, sized by ``MXNET_TPU_SERVE_KV_BLOCKS``
× ``MXNET_TPU_SERVE_KV_BLOCK``) and **fixed-shape AOT programs**
(`ServePrograms`: ONE multi-stream chunk-prefill window, ONE decode
executable, a CoW block copy, and the draft/verify pair when speculative
decoding is configured — every signature compiled at warm-up, so
admission never retraces mid-traffic). `ReplicaGroup` supervises N
replicas over one shared queue.

Serving v2 throughput layers, all attributable in telemetry and
`BENCH=serve`: burst arrivals prefill TOGETHER in chunk windows
interleaved with decode (``serve.prefill_chunks``); N users of one
system prompt share its KV blocks by refcount with copy-on-write at the
divergence block (``serve.prefix.*``); a small draft model multiplies
greedy tokens/s where decode is HBM-bound (``serve.spec.*``,
byte-identical output by construction); and temperature/top-k/top-p
sampling draws are keyed on (stream seed, position) so kill-recovery
replays them exactly.

The robustness contract, end to end:

* structured `Overloaded` load-shedding when the queue or KV pool is
  exhausted — never an OOM;
* per-request deadlines (`DeadlineExceeded` carries partial output) and
  retry budgets (``MXNET_TPU_RETRIES``);
* ``serve.admit`` / ``serve.step`` fault sites under
  ``MXNET_TPU_FAULT_PLAN``, the hang watchdog around the decode loop;
* kill-a-replica-mid-stream recovery: the replica drains, its in-flight
  streams re-enter the queue and resume via re-prefill from their
  already-emitted tokens — byte-identical output, no token lost or
  duplicated;
* telemetry throughout: tokens/s, TTFT/TPOT histograms, queue depth and
  KV occupancy gauges, flight-recorder ``step_event`` records (with the
  active/completed request ids per step), and a `RequestTrace` per
  request — queue-wait / prefill / per-token decode / recovery spans
  tiling its wall-clock, queryable via the exporter's ``/requests``
  endpoint (`mx.telemetry.request_traces()`), embedded in
  ``DeadlineExceeded.request_trace``, one chrome-trace row per request.

Quickstart::

    import mxnet_tpu as mx
    from mxnet_tpu.models.llama import CONFIGS, llama_init
    import jax

    cfg = CONFIGS["llama_110m"]
    params = llama_init(jax.random.PRNGKey(0), cfg)
    server = mx.serve.InferenceServer(params, cfg).warmup()
    h = server.submit(mx.serve.Request([1, 2, 3], max_new_tokens=32))
    server.run()              # or ReplicaGroup(...).start() for a fleet
    print(h.result())
"""
from __future__ import annotations

from .errors import DeadlineExceeded, Overloaded, ServeError
from .kv_cache import KVBlockPool
from .programs import (ServePrograms, default_chunk_size,
                       default_prefill_rows, default_spec_k)
from .replica import ReplicaGroup
from .sampling import sample_tokens
from .scheduler import (InferenceServer, Request, RequestQueue,
                        StreamHandle)

__all__ = ["ServeError", "Overloaded", "DeadlineExceeded", "KVBlockPool",
           "ServePrograms", "default_chunk_size", "default_prefill_rows",
           "default_spec_k", "sample_tokens", "InferenceServer",
           "Request", "RequestQueue", "StreamHandle", "ReplicaGroup"]
