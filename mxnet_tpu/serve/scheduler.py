"""Continuous-batching scheduler: admission, decode loop, fault recovery.

The serving analog of `resilience.run.ResilientRunner`: one replica =
one `InferenceServer`, driving the AOT programs (`serve.programs`) over the
paged KV pool (`serve.kv_cache`) with the full robustness contract wired
through the existing planes —

* **continuous batching** — requests join and leave the running batch
  *between* decode steps (the way the bucketed comm engine overlaps
  buckets): a fresh request is admitted into any free batch slot, prefilled
  at its bucket, and decodes alongside whatever is already running; a
  finished stream frees its slot and blocks immediately;
* **admission control** — a full queue or an exhausted KV pool answers
  with a structured `Overloaded` (shed, never OOM); a request whose
  worst-case context can NEVER fit is shed at submit; a transiently
  unfit one simply waits its turn in the queue (backpressure);
* **deadlines & retry budgets** — each request carries an optional
  deadline (checked in the queue and mid-stream; partial output travels on
  the `DeadlineExceeded`) and a retry budget sourced from
  `resilience.retry.RetryPolicy` (``MXNET_TPU_RETRIES``);
* **fault sites** — ``serve.admit`` (submission) and ``serve.step`` (top
  of every scheduler step) are `resilience.faults` sites, so
  ``MXNET_TPU_FAULT_PLAN`` chaos plans cover serving exactly like
  training; the step body runs under the hang watchdog
  (``MXNET_TPU_SERVE_STEP_DEADLINE_S``, falling back to the global step
  deadline), so a dead decode becomes a recoverable `StallError`;
* **drain & resume** — any retriable fault drains the replica: every
  in-flight stream's blocks are freed and the stream re-enters the queue
  (front, budget decremented), to resume — here or on another replica —
  by **re-prefilling its prompt + already-emitted tokens**. Greedy decode
  plus the bit-matching paged/prefill math make the resumed output
  byte-identical: no token is lost (emitted tokens are the new context)
  and none duplicated (the resumed prefill emits the FIRST not-yet-seen
  token).

Telemetry: ``serve.requests/admitted/completed/shed[.reason]/tokens/
prefills/decode_steps/recoveries/requeued_streams/failed`` counters,
``serve.queue_depth`` / ``serve.batch_occupancy`` / ``serve.kv.*`` gauges,
``serve.ttft_ms`` / ``serve.tpot_ms`` / ``serve.step_ms`` histograms, a
``serve.step`` span per step (cat ``step`` — the attribution profiler's
serving window), and ``telemetry.step_event("serve.step", ms)`` per step
with the active/completed request ids — anomaly detection and the crash
flight recorder cover the serving path for free.

Per-request tracing (`telemetry.request_trace`): a `RequestTrace` is
created at enqueue and rides the `StreamHandle` through admit → prefill →
every decode step → completion/shed/recovery — across replica boundaries,
since a drained stream keeps its handle. Its spans TILE the request's
wall-clock (queue / prefill / decode / recovery.drain / recovery.queue);
completed timelines land in the last-N ring (``/requests`` endpoint,
``parse_log --requests``), ride ``DeadlineExceeded.request_trace``, and
replay into chrome dumps as one row per request. Inert under
``MXNET_TPU_TELEMETRY=0`` / ``MXNET_TPU_SERVE_TRACE=0``.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque

import numpy as np

from .. import telemetry as _telem
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from ..resilience.errors import RetriableError, RetryExhausted
from ..resilience.retry import RetryPolicy
from ..telemetry import flight as _flight
from ..telemetry import request_trace as _reqtrace
from .errors import DeadlineExceeded, Overloaded
from .kv_cache import KVBlockPool
from .programs import ServePrograms

__all__ = ["Request", "StreamHandle", "RequestQueue", "InferenceServer",
           "default_max_batch", "default_queue_cap"]


def default_max_batch():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_MAX_BATCH", "8")))
    except (TypeError, ValueError):
        return 8


def default_queue_cap():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_QUEUE", "64")))
    except (TypeError, ValueError):
        return 64


def _step_deadline_s():
    raw = os.environ.get("MXNET_TPU_SERVE_STEP_DEADLINE_S")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return _watchdog.default_deadline_s()


class Request:
    """One generation request: a token prompt plus its budgets.

    deadline_s is relative to submission and covers queue wait AND decode;
    eos_id stops the stream early; retries overrides the replica-fault
    budget (default: `RetryPolicy().max_attempts`, i.e. MXNET_TPU_RETRIES).
    """

    def __init__(self, prompt, max_new_tokens=16, request_id=None,
                 deadline_s=None, eos_id=None, retries=None):
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("serve: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("serve: max_new_tokens must be >= 1")
        # str-coerced: the id is joined into log lines, flight records,
        # and recovery post-mortems, which assume string ids
        self.request_id = (str(request_id) if request_id
                           else uuid.uuid4().hex[:12])
        self.deadline_s = deadline_s
        self.eos_id = eos_id
        self.retries = retries


class StreamHandle:
    """The caller's view of an in-flight stream: tokens appear as decoded,
    `result()` blocks for completion. Survives replica kills — a requeued
    stream keeps its handle, so recovery is invisible to the client except
    for `requeues` ticking up."""

    def __init__(self, request):
        self.request = request
        self.id = request.request_id
        self.tokens = []          # emitted tokens, grown by the scheduler
        self.error = None
        self.ttft_ms = None
        self.tpot_ms = []         # per-output-token latencies after the 1st
        self.requeues = 0
        # the request's own timeline (telemetry.request_trace), created at
        # enqueue; it lives on the HANDLE so it crosses replica boundaries
        # with the stream — a drained request resumed on a survivor keeps
        # ONE trace. NULL_TRACE (no-op) until submit attaches a live one.
        self.trace = _reqtrace.NULL_TRACE
        self._done = threading.Event()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the stream completes; returns the emitted tokens or
        raises the structured error that ended it."""
        if not self._done.wait(timeout):
            raise TimeoutError("serve: stream %s still running" % self.id)
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def _complete(self):
        self._done.set()

    def _fail(self, exc):
        self.error = exc
        self._done.set()


class _Stream:
    """Scheduler-internal in-flight state. `handle.tokens` IS the emitted
    list — requeue/resume carries it untouched."""

    __slots__ = ("handle", "request", "retries_left", "deadline",
                 "last_token_t", "t_submit", "owner", "table_row",
                 "kv_id")

    def __init__(self, handle, retries_left):
        self.handle = handle
        self.request = handle.request
        self.retries_left = retries_left
        # `is not None`: deadline_s=0 means "already expired", not "none"
        self.deadline = (time.monotonic() + handle.request.deadline_s
                         if handle.request.deadline_s is not None else None)
        # the KV pool is keyed by THIS key, never by the caller-supplied
        # request_id: two in-flight requests reusing one id must not
        # silently share (and cross-corrupt) one block table
        self.kv_id = uuid.uuid4().hex[:12]
        self.last_token_t = None
        self.t_submit = time.perf_counter()
        # who holds the stream right now — the RequestQueue instance when
        # queued, the InferenceServer that popped it while in flight.
        # Written ONLY under the queue lock; recovery decisions read it
        # there too, so "has my requeue already run / did another replica
        # already take this stream" is answered atomically (a plain
        # membership check would race a sibling replica's pop)
        self.owner = None
        # padded block-table row, cached at admission: the table is
        # immutable for the stream's in-flight life (worst-case blocks
        # reserved up front), so the decode hot path must not rebuild it
        # per token
        self.table_row = None

    @property
    def context(self):
        return self.request.prompt + self.handle.tokens

    def expired(self, now):
        return self.deadline is not None and now > self.deadline

    def finished(self):
        """Emitted everything it ever will (budget spent, or EOS) — but
        not yet retired. Normally _finish_check retires in the same step;
        a requeued stream can arrive in this state when a fault landed in
        between."""
        tokens = self.handle.tokens
        if len(tokens) >= self.request.max_new_tokens:
            return True
        return (self.request.eos_id is not None and tokens
                and tokens[-1] == self.request.eos_id)


class RequestQueue:
    """Bounded admission queue, shareable across replicas. `push` sheds at
    capacity; `requeue` (recovery re-entry) is cap-exempt and goes to the
    FRONT — a stream must never be shed by its own replica's death."""

    def __init__(self, cap=None):
        self.cap = int(cap or default_queue_cap())
        self._items = deque()
        self._cond = threading.Condition()

    def push(self, stream):
        with self._cond:
            if len(self._items) >= self.cap:
                raise Overloaded(
                    "serve queue full (%d waiting, cap %d)"
                    % (len(self._items), self.cap),
                    reason="queue_full", queue_depth=len(self._items),
                    retry_after_s=0.1)
            stream.owner = self
            self._items.append(stream)
            depth = len(self._items)
            self._cond.notify_all()
        _telem.set_gauge("serve.queue_depth", depth)

    def requeue(self, stream):
        with self._cond:
            stream.owner = self
            self._items.appendleft(stream)
            depth = len(self._items)
            self._cond.notify_all()
        _telem.set_gauge("serve.queue_depth", depth)

    def pop(self, owner=None):
        """Pop the head stream, atomically transferring ownership to
        `owner` (the popping replica) under the queue lock."""
        with self._cond:
            if not self._items:
                return None
            stream = self._items.popleft()
            stream.owner = owner
            depth = len(self._items)
        _telem.set_gauge("serve.queue_depth", depth)
        return stream

    def owned_by(self, stream, who):
        """Atomic ownership check — recovery's 'is this mid-admission
        stream still MINE to drain, or did my requeue already hand it
        off (possibly straight into a sibling replica's pop)?'"""
        with self._cond:
            return stream.owner is who

    def wait_nonempty(self, timeout=None):
        with self._cond:
            if self._items:
                return True
            self._cond.wait(timeout)
            return bool(self._items)

    def __len__(self):
        with self._cond:
            return len(self._items)


class InferenceServer:
    """One fault-tolerant continuous-batching serving replica.

    Usage::

        server = mx.serve.InferenceServer(params, cfg)
        server.warmup()                       # AOT-compile all programs
        h = server.submit(mx.serve.Request([1, 2, 3], max_new_tokens=8))
        server.run()                          # drive until idle
        print(h.result())
    """

    def __init__(self, params, cfg, *, max_batch=None, kv_blocks=None,
                 block_size=None, max_context=None, buckets=None,
                 queue=None, queue_cap=None, step_deadline_s=None,
                 max_restarts=3, name="replica0"):
        self.name = name
        self.cfg = cfg
        self.pool = KVBlockPool(cfg, num_blocks=kv_blocks,
                                block_size=block_size)
        if max_context is None:
            max_context = min(cfg.max_seq_len,
                              self.pool.num_blocks * self.pool.block_size)
        self.max_batch = int(max_batch or default_max_batch())
        self.programs = ServePrograms(params, cfg, self.pool,
                                      self.max_batch, max_context,
                                      buckets=buckets)
        self.queue = queue if queue is not None else RequestQueue(queue_cap)
        self.step_deadline_s = (step_deadline_s if step_deadline_s
                                is not None else _step_deadline_s())
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.dead = False
        self._slots = [None] * self.max_batch
        # the stream currently mid-admission (popped from the queue but
        # not yet in a slot): a fault landing inside _admit — including
        # the watchdog's ASYNC StallError, which can fire between any two
        # bytecodes of the prefill — must find it here, or recovery would
        # drain only _slots and silently lose the stream
        self._admitting = None
        # request ids retired during the CURRENT step — reset at step
        # start, embedded (with the active set) in the step's flight
        # record so a stall post-mortem names the in-flight requests
        self._step_completed = []
        self._default_retries = RetryPolicy().max_attempts

    # ------------------------------------------------------------ admission
    def _worst_blocks(self, request):
        """Blocks reserved at admission: the FULL possible context. Greedy
        reservation keeps the invariant that an admitted stream can always
        finish — mid-stream KV exhaustion cannot exist. The final emitted
        token's KV is never written (the stream retires before feeding
        it), so the worst case is prompt + max_new_tokens - 1 positions."""
        return self.pool.blocks_for(len(request.prompt)
                                    + request.max_new_tokens - 1)

    def _note_shed(self, reason, detail=""):
        _telem.inc("serve.shed")
        _telem.inc("serve.shed.%s" % reason)
        _flight.note_event("serve_shed",
                           "%s %s" % (reason, detail) if detail else reason)

    def _shed(self, exc, reason):
        self._note_shed(reason)
        raise exc

    def submit(self, request):
        """Admit a request into the queue; returns a `StreamHandle`.
        Raises `Overloaded` (structured, never an OOM later) when the
        queue is full or the request can never fit the KV pool/buckets."""
        _faults.check("serve.admit", context="request=%s"
                      % request.request_id)
        _telem.inc("serve.requests")
        # the request's trace starts at enqueue: even a shed request
        # leaves a timeline in the last-N ring (/requests)
        trace = _reqtrace.start(request.request_id)
        # the longest context this request can ever re-prefill (a resumed
        # stream prefills prompt + all-but-one emitted budget)
        max_prefill = len(request.prompt) + request.max_new_tokens - 1
        # the explicit max_context bound matters when the last bucket
        # rounded UP past it (block alignment): bucket existence alone
        # would admit positions beyond the model's trained context
        if (self._worst_blocks(request) > self.pool.num_blocks
                or self.programs.bucket_for(max_prefill) is None
                or max_prefill > self.programs.max_context):
            trace.finish("shed.too_large", tokens=0)
            self._shed(Overloaded(
                "request %s can never fit: prompt %d + budget %d tokens "
                "vs pool of %d blocks x %d (max context %d)"
                % (request.request_id, len(request.prompt),
                   request.max_new_tokens, self.pool.num_blocks,
                   self.pool.block_size, self.programs.max_context),
                reason="too_large",
                kv_needed_blocks=self._worst_blocks(request),
                kv_free_blocks=self.pool.free_blocks), "too_large")
        handle = StreamHandle(request)
        handle.trace = trace
        retries = (request.retries if request.retries is not None
                   else self._default_retries)
        stream = _Stream(handle, retries_left=retries)
        try:
            self.queue.push(stream)
        except Overloaded:
            trace.finish("shed.queue_full", tokens=0)
            self._note_shed("queue_full")
            raise
        return handle

    # ------------------------------------------------------------- stepping
    def warmup(self):
        self.programs.warmup()
        return self

    def _free_slot(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _finish_trace(self, handle, outcome):
        """Snapshot the request's trace into the last-N ring (idempotent:
        an earlier, more specific finish wins)."""
        ttft = (round(handle.ttft_ms, 3) if handle.ttft_ms is not None
                else None)
        return handle.trace.finish(outcome, tokens=len(handle.tokens),
                                   ttft_ms=ttft,
                                   requeues=handle.requeues)

    def _retire(self, slot, stream, error=None):
        # terminal event FIRST: if an async fault lands mid-retire, the
        # stream is done-marked while still findable in its slot, and
        # _drain_stream's done() branch finishes the cleanup — the other
        # order would strand a finished stream in neither place
        if error is not None:
            self._finish_trace(stream.handle,
                               "deadline" if isinstance(
                                   error, DeadlineExceeded) else "failed")
            stream.handle._fail(error)
        else:
            self._finish_trace(stream.handle, "completed")
            _telem.inc("serve.completed")
            stream.handle._complete()
        self._step_completed.append(stream.handle.id)
        self.pool.free(stream.kv_id)
        self._slots[slot] = None

    def _finish_check(self, slot, stream, token, now):
        handle = stream.handle
        request = stream.request
        if stream.expired(now):
            self._note_shed("deadline", stream.handle.id)
            payload = self._finish_trace(handle, "deadline")
            self._retire(slot, stream, DeadlineExceeded(
                "request %s missed its %.3gs deadline after %d token(s)"
                % (request.request_id, request.deadline_s,
                   len(handle.tokens)), tokens=handle.tokens,
                request_trace=payload))
            return True
        if (len(handle.tokens) >= request.max_new_tokens
                or (request.eos_id is not None
                    and token == request.eos_id)):
            self._retire(slot, stream)
            return True
        return False

    def _admit(self):
        """Fill free batch slots from the queue: pop → reserve KV → prefill
        (prompt + any already-emitted tokens — the resume path) → join the
        running batch. A transiently unfit head request goes back to the
        front and admission stops (backpressure, streams keep decoding)."""
        admitted = 0
        while True:
            slot = self._free_slot()
            if slot is None:
                break
            # chained assignment marks the stream in-flight in the same
            # statement that pops it (a fault can land at any bytecode
            # boundary; the remaining pop->mark window is ~one store);
            # the pop also transfers queue-lock-governed ownership to us
            self._admitting = stream = self.queue.pop(self)
            if stream is None:
                break
            # the wait just ended: close it on the request's timeline
            # ("queue", or "recovery.queue" after a drain) and record
            # which replica now holds the stream — the cross-replica hop
            # list of a recovered request
            trace = stream.handle.trace
            trace.mark("queue", replica=self.name).note_replica(self.name)
            if stream.finished():
                # a fault landed between the stream's last token and its
                # _finish_check: it came back complete — retire it here
                # instead of re-prefilling one token too many
                self._finish_trace(stream.handle, "completed")
                self._step_completed.append(stream.handle.id)
                _telem.inc("serve.completed")
                stream.handle._complete()
                self._admitting = None
                continue
            now = time.monotonic()
            if stream.expired(now):
                self._note_shed("deadline", stream.handle.id)
                payload = self._finish_trace(stream.handle, "deadline")
                self._step_completed.append(stream.handle.id)
                stream.handle._fail(DeadlineExceeded(
                    "request %s missed its %.3gs deadline in the queue"
                    % (stream.handle.id, stream.request.deadline_s),
                    tokens=stream.handle.tokens, request_trace=payload))
                self._admitting = None
                continue
            try:
                self.pool.alloc(stream.kv_id,
                                len(stream.request.prompt)
                                + stream.request.max_new_tokens - 1)
            except Overloaded:
                # transient: the pool drains as running streams finish
                self.queue.requeue(stream)
                self._admitting = None
                break
            # the table is immutable for the stream's in-flight life
            # (worst case reserved above): build the padded row once,
            # decode reuses it every step
            stream.table_row = self.pool.table(
                stream.kv_id, self.programs.blocks_per_stream)
            context = stream.context
            width = self.programs.bucket_for(len(context))
            table = stream.table_row[:width // self.pool.block_size]
            t0 = time.perf_counter()
            token = self.programs.prefill(context, table)
            _telem.inc("serve.prefills")
            _telem.observe("serve.prefill_ms",
                           (time.perf_counter() - t0) * 1e3)
            now = time.monotonic()
            stream.handle.tokens.append(token)
            stream.last_token_t = now
            trace.mark("prefill", tokens=len(context), bucket=width)
            _telem.inc("serve.tokens")
            if stream.handle.ttft_ms is None:
                # time-to-first-token counts the queue wait, not just the
                # prefill — that is the latency the client experienced
                stream.handle.ttft_ms = (time.perf_counter()
                                         - stream.t_submit) * 1e3
                _telem.observe("serve.ttft_ms", stream.handle.ttft_ms)
            self._slots[slot] = stream
            self._admitting = None
            _telem.inc("serve.admitted")
            admitted += 1
            self._finish_check(slot, stream, token, now)
        return admitted

    def _decode(self):
        """One decode step over every active slot (fixed program shape:
        inactive slots ride along masked)."""
        active = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return 0
        tokens = np.zeros(self.max_batch, np.int32)
        positions = np.full(self.max_batch, -1, np.int32)
        tables = np.full((self.max_batch, self.programs.blocks_per_stream),
                         self.pool.num_blocks, np.int32)
        for i, s in active:
            tokens[i] = s.handle.tokens[-1]
            positions[i] = len(s.context) - 1
            tables[i] = s.table_row
        out = self.programs.decode(tokens, positions, tables)
        _telem.inc("serve.decode_steps")
        now = time.monotonic()
        for i, s in active:
            token = int(out[i])
            s.handle.tokens.append(token)
            _telem.inc("serve.tokens")
            if s.last_token_t is not None:
                tpot = (now - s.last_token_t) * 1e3
                s.handle.tpot_ms.append(tpot)
                _telem.observe("serve.tpot_ms", tpot)
            s.last_token_t = now
            # one decode span per emitted token: the inter-token interval,
            # so slot residency tiles the request's timeline completely
            s.handle.trace.mark("decode", token=len(s.handle.tokens))
            self._finish_check(i, s, token, now)
        return len(active)

    def step(self):
        """One scheduler iteration: (maybe) admit, (maybe) decode. Returns
        True while there is in-flight or queued work. Raises the injected/
        real `RetriableError`s the recovery path (`run`) absorbs."""
        if not self.programs._warm:
            self.warmup()
        t0 = time.perf_counter()
        ts = _telem.span_clock()
        self._step_completed = []
        with _watchdog.guard("serve.step", deadline_s=self.step_deadline_s):
            _faults.check("serve.step", context="replica=%s" % self.name)
            admitted = self._admit()
            decoded = self._decode()
        occupancy = sum(1 for s in self._slots if s is not None)
        _telem.set_gauge("serve.batch_occupancy", occupancy)
        # admission-only steps (e.g. a max_new_tokens=1 request retired at
        # prefill) must still land in the step plane, or their completed
        # ids never reach a flight post-mortem
        if decoded or admitted or self._step_completed:
            dur = time.perf_counter() - t0
            _telem.observe("serve.step_ms", dur * 1e3)
            # the serving cadence joins the step-span plane: attribution
            # decomposes these windows exactly like training steps
            _telem.record_span("serve.step", "step", ts, dur)
            info = {"active_requests":
                    [s.handle.id for s in self._slots
                     if s is not None][:16]}
            if self._step_completed:
                info["completed_requests"] = self._step_completed[:16]
            _telem.step_event("serve.step", dur * 1e3, info=info)
        return occupancy > 0 or len(self.queue) > 0

    # ------------------------------------------------------------- recovery
    def _drain_stream(self, stream, exc):
        """Free one in-flight stream's blocks and send it back to the
        queue (front, budget decremented) — or fail it when the budget is
        spent. Returns 1 when the stream was requeued."""
        stream.table_row = None     # blocks are going back to the pool
        if stream.handle.done():
            # retirement's terminal event already fired when the fault
            # landed; only the pool/slot cleanup remained
            self.pool.free(stream.kv_id)
            return 0
        self.pool.free(stream.kv_id)
        stream.retries_left -= 1
        if stream.retries_left < 0:
            _telem.inc("serve.failed")
            stream.handle.trace.mark("recovery.drain",
                                     error=type(exc).__name__)
            self._finish_trace(stream.handle, "failed")
            stream.handle._fail(RetryExhausted(
                "stream %s: replica-fault retry budget spent; last "
                "error: %s: %s" % (stream.handle.id,
                                   type(exc).__name__, exc),
                site="serve.step", last_error=exc))
            return 0
        stream.handle.requeues += 1
        # timeline: activity → fault is "recovery.drain"; the wait until
        # re-admission (here or on a surviving replica) will close as
        # "recovery.queue" — the recovery cost is fully attributed
        stream.handle.trace.note_drain(exc)
        self.queue.requeue(stream)
        _telem.inc("serve.requeued_streams")
        return 1

    def _recover(self, exc):
        """Drain after a replica fault: every in-flight stream — the batch
        slots AND a stream caught mid-admission — frees its blocks and
        re-enters the shared queue (front) to resume, here or on a
        surviving replica, by re-prefill. Budget-exhausted streams fail
        with `RetryExhausted` instead of looping forever."""
        drained = 0
        requeued_ids, lost_ids = [], []

        def drain(stream):
            nonlocal drained
            n = self._drain_stream(stream, exc)
            drained += n
            # n=0 means the stream did NOT resume (retry budget spent, or
            # already done) — the post-mortem must not claim it was
            (requeued_ids if n else lost_ids).append(stream.handle.id)

        admitting, self._admitting = self._admitting, None
        if admitting is not None and not admitting.handle.done() \
                and self.queue.owned_by(admitting, self):
            # drain the mid-admission stream ONLY if it is still OURS:
            # if the fault landed in the one-bytecode window after our
            # requeue ran (ownership already handed to the queue — or
            # beyond, to a sibling replica's pop), a second requeue would
            # admit one stream into two slots. The owner field is written
            # and read under the queue lock, so this cannot race a
            # sibling's pop the way a membership check would.
            drain(admitting)
        for i, stream in enumerate(self._slots):
            if stream is None:
                continue
            self._slots[i] = None
            if stream is admitting:
                # the fault landed between slot assignment and the
                # _admitting clear: the stream is in BOTH places — drain
                # it once, or two admissions would share one handle and
                # one block table (duplicated, corrupted output)
                continue
            drain(stream)
        # a fault between a donating program call and pool.update leaves
        # deleted pool buffers; every stream re-prefills anyway, so just
        # re-materialize the storage
        self.pool.ensure_storage()
        # ... and one landing inside an alloc/free can tear the free-list
        # (blocks in neither a table nor the list): rebuild it as the
        # complement of the surviving tables
        self.pool.reconcile()
        _telem.inc("serve.recoveries")
        # the drain post-mortem names the requests it touched, not just a
        # count — the flight ring's serve_recover event IS the answer to
        # "whose streams did that dead replica hold?"
        msg = ("%s: %s (requeued %d: %s)"
               % (self.name, type(exc).__name__, drained,
                  ",".join(requeued_ids[:8]) if requeued_ids else "-"))
        if lost_ids:
            msg += " (not requeued: %s)" % ",".join(lost_ids[:8])
        _flight.note_event("serve_recover", msg)
        return drained

    def run(self, max_steps=None, stop=None):
        """Drive the scheduler: until idle (stop=None — the batch-drain
        mode tests and benches use), or until `stop` (an Event) is set —
        the long-lived replica-thread mode, parking on the queue when
        idle. Retriable faults drain-and-continue up to `max_restarts`;
        past the budget the replica re-raises (marked `dead`) with its
        streams already requeued for the survivors."""
        steps = 0
        t0 = time.perf_counter()
        tokens0 = _telem.registry.counter("serve.tokens").value
        try:
            while stop is None or not stop.is_set():
                try:
                    busy = self.step()
                except RetriableError as exc:
                    self._recover(exc)
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        self.dead = True
                        _telem.inc("serve.replica_deaths")
                        raise
                    continue
                except Exception as exc:
                    # a NON-retriable escape (device loss surfacing as a
                    # runtime error, a programming bug) still must not
                    # strand in-flight streams: drain them to the queue
                    # for the survivors, then die
                    self._recover(exc)
                    self.dead = True
                    _telem.inc("serve.replica_deaths")
                    raise
                steps += 1
                if max_steps is not None and steps >= max_steps:
                    break
                if not busy:
                    if stop is None:
                        break
                    self.queue.wait_nonempty(timeout=0.05)
        finally:
            dt = time.perf_counter() - t0
            if dt > 0:
                tokens = (_telem.registry.counter("serve.tokens").value
                          - tokens0)
                _telem.set_gauge("serve.tokens_per_s", round(tokens / dt, 2))
        return steps
