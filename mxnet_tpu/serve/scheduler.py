"""Continuous-batching scheduler: admission, chunked prefill, decode, spec.

The serving analog of `resilience.run.ResilientRunner`: one replica =
one `InferenceServer`, driving the AOT programs (`serve.programs`) over the
paged KV pool (`serve.kv_cache`) with the full robustness contract wired
through the existing planes —

* **continuous batching** — requests join and leave the running batch
  *between* decode steps (the way the bucketed comm engine overlaps
  buckets): a fresh request is admitted into any free batch slot and
  decodes alongside whatever is already running; a finished stream frees
  its slot and blocks immediately;
* **chunked, batched prefill** — prompt work is cut into fixed-shape
  (rows × chunk) windows interleaved with decode under a per-step token
  budget (``MXNET_TPU_SERVE_PREFILL_BUDGET``): a burst of arrivals
  prefills TOGETHER in one program instead of serializing TTFT behind
  batch-1 prefills, and a long prompt cannot starve running decodes;
* **prefix sharing** — admission looks the stream's context up in the
  pool's hash-consed prefix index (`KVBlockPool.admit`): full blocks of
  an already-cached prompt prefix join the table by refcount and prefill
  skips their positions (copy-on-write at the divergence block), so N
  users of one system prompt pay for its KV once;
* **speculative decoding** — when a draft model is configured, greedy
  streams decode via a draft-k / verify-k acceptance loop (`serve.spec.*`
  counters): the tiny draft proposes ``spec_k`` tokens in one program,
  the target model verifies them all in one chunk-shaped pass, and every
  accepted token skips a full decode dispatch — byte-identical to the
  non-speculative greedy path by construction (only tokens the target's
  own argmax agrees with are ever emitted);
* **sampling** — per-request temperature/top-k/top-p ride the programs as
  per-slot vectors (`serve.sampling`); draws key on (stream seed,
  position), so a recovered stream replays the same tokens. Sampled
  streams take the plain decode path (spec stays greedy-verify);
* **admission control** — a full queue or an exhausted KV pool answers
  with a structured `Overloaded` (shed, never OOM); a request whose
  worst-case context can NEVER fit is shed at submit; a transiently
  unfit one simply waits its turn in the queue (backpressure);
* **deadlines & retry budgets** — each request carries an optional
  deadline (checked in the queue and mid-stream; partial output travels on
  the `DeadlineExceeded`) and a retry budget sourced from
  `resilience.retry.RetryPolicy` (``MXNET_TPU_RETRIES``);
* **fault sites** — ``serve.admit`` (submission) and ``serve.step`` (top
  of every scheduler step) are `resilience.faults` sites, so
  ``MXNET_TPU_FAULT_PLAN`` chaos plans cover serving exactly like
  training; the step body runs under the hang watchdog
  (``MXNET_TPU_SERVE_STEP_DEADLINE_S``, falling back to the global step
  deadline), so a dead decode becomes a recoverable `StallError`;
* **drain & resume** — any retriable fault drains the replica: every
  in-flight stream's blocks are freed (refcount-exactly — a shared
  prefix block under a live sibling survives) and the stream re-enters
  the queue (front, budget decremented), to resume — here or on another
  replica — by **re-prefilling its prompt + already-emitted tokens**.
  Deterministic decode (greedy argmax, or position-keyed sampling) plus
  the bit-matching paged chunk math make the resumed output
  byte-identical: no token is lost (emitted tokens are the new context)
  and none duplicated (the resumed prefill emits the FIRST not-yet-seen
  token).

Telemetry: ``serve.requests/admitted/completed/shed[.reason]/tokens/
prefills/prefill_chunks/decode_steps/recoveries/requeued_streams/failed``
counters, the prefix story (``serve.prefix.*`` from the pool), the spec
story (``serve.spec.drafted/accepted/rejected/rounds``),
``serve.queue_depth`` / ``serve.batch_occupancy`` / ``serve.kv.*`` gauges,
``serve.ttft_ms`` / ``serve.tpot_ms`` / ``serve.step_ms`` histograms, a
``serve.step`` span per step (cat ``step`` — the attribution profiler's
serving window), and ``telemetry.step_event("serve.step", ms)`` per step
with the active/completed request ids — anomaly detection and the crash
flight recorder cover the serving path for free.

Per-request tracing (`telemetry.request_trace`): a `RequestTrace` is
created at enqueue and rides the `StreamHandle` through admit → prefill →
every decode step → completion/shed/recovery — across replica boundaries,
since a drained stream keeps its handle. Its spans TILE the request's
wall-clock (queue / prefill / decode / recovery.drain / recovery.queue);
completed timelines land in the last-N ring (``/requests`` endpoint,
``parse_log --requests``), ride ``DeadlineExceeded.request_trace``, and
replay into chrome dumps as one row per request. Inert under
``MXNET_TPU_TELEMETRY=0`` / ``MXNET_TPU_SERVE_TRACE=0``.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
import zlib
from collections import deque

import numpy as np

from .. import telemetry as _telem
from ..resilience import faults as _faults
from ..resilience import watchdog as _watchdog
from ..resilience.errors import RetriableError, RetryExhausted
from ..resilience.retry import RetryPolicy
from ..telemetry import flight as _flight
from ..telemetry import request_trace as _reqtrace
from .errors import DeadlineExceeded, Overloaded
from .kv_cache import KVBlockPool
from .programs import ServePrograms

__all__ = ["Request", "StreamHandle", "RequestQueue", "InferenceServer",
           "default_max_batch", "default_queue_cap"]


def default_max_batch():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_MAX_BATCH", "8")))
    except (TypeError, ValueError):
        return 8


def default_queue_cap():
    try:
        return max(1, int(os.environ.get("MXNET_TPU_SERVE_QUEUE", "64")))
    except (TypeError, ValueError):
        return 64


def default_prefill_budget(rows, chunk):
    try:
        raw = int(os.environ.get("MXNET_TPU_SERVE_PREFILL_BUDGET", "0"))
    except (TypeError, ValueError):
        raw = 0
    return raw if raw > 0 else rows * chunk


def _step_deadline_s():
    raw = os.environ.get("MXNET_TPU_SERVE_STEP_DEADLINE_S")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return _watchdog.default_deadline_s()


class Request:
    """One generation request: a token prompt plus its budgets and
    sampling policy.

    deadline_s is relative to submission and covers queue wait AND decode;
    eos_id stops the stream early; retries overrides the replica-fault
    budget (default: `RetryPolicy().max_attempts`, i.e. MXNET_TPU_RETRIES).
    temperature <= 0 is greedy (the default); top_k/top_p filter the
    sampled distribution; seed pins the sampling draws (default: derived
    from request_id, so retries of one request replay the same tokens).
    """

    def __init__(self, prompt, max_new_tokens=16, request_id=None,
                 deadline_s=None, eos_id=None, retries=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=None):
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("serve: empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("serve: max_new_tokens must be >= 1")
        # str-coerced: the id is joined into log lines, flight records,
        # and recovery post-mortems, which assume string ids
        self.request_id = (str(request_id) if request_id
                           else uuid.uuid4().hex[:12])
        self.deadline_s = deadline_s
        self.eos_id = eos_id
        self.retries = retries
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        if self.top_k < 0:
            raise ValueError("serve: top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("serve: top_p must be in (0, 1]")
        # the replay key: stable across requeues/replicas by construction
        self.seed = (int(seed) if seed is not None
                     else zlib.crc32(self.request_id.encode())) & 0xffffffff

    @property
    def greedy(self):
        return self.temperature <= 0.0


class StreamHandle:
    """The caller's view of an in-flight stream: tokens appear as decoded,
    `result()` blocks for completion. Survives replica kills — a requeued
    stream keeps its handle, so recovery is invisible to the client except
    for `requeues` ticking up."""

    def __init__(self, request):
        self.request = request
        self.id = request.request_id
        self.tokens = []          # emitted tokens, grown by the scheduler
        self.error = None
        self.ttft_ms = None
        self.tpot_ms = []         # per-output-token latencies after the 1st
        self.requeues = 0
        # the request's own timeline (telemetry.request_trace), created at
        # enqueue; it lives on the HANDLE so it crosses replica boundaries
        # with the stream — a drained request resumed on a survivor keeps
        # ONE trace. NULL_TRACE (no-op) until submit attaches a live one.
        self.trace = _reqtrace.NULL_TRACE
        self._done = threading.Event()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the stream completes; returns the emitted tokens or
        raises the structured error that ended it."""
        if not self._done.wait(timeout):
            raise TimeoutError("serve: stream %s still running" % self.id)
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    def _complete(self):
        self._done.set()

    def _fail(self, exc):
        self.error = exc
        self._done.set()


class _Stream:
    """Scheduler-internal in-flight state. `handle.tokens` IS the emitted
    list — requeue/resume carries it untouched."""

    __slots__ = ("handle", "request", "retries_left", "deadline",
                 "last_token_t", "t_submit", "owner", "table_row",
                 "kv_id", "fill_pos", "fill_len", "fill_chunks")

    def __init__(self, handle, retries_left):
        self.handle = handle
        self.request = handle.request
        self.retries_left = retries_left
        # `is not None`: deadline_s=0 means "already expired", not "none"
        self.deadline = (time.monotonic() + handle.request.deadline_s
                         if handle.request.deadline_s is not None else None)
        # the KV pool is keyed by THIS key, never by the caller-supplied
        # request_id: two in-flight requests reusing one id must not
        # silently share (and cross-corrupt) one block table
        self.kv_id = uuid.uuid4().hex[:12]
        self.last_token_t = None
        self.t_submit = time.perf_counter()
        # who holds the stream right now — the RequestQueue instance when
        # queued, the InferenceServer that popped it while in flight.
        # Written ONLY under the queue lock; recovery decisions read it
        # there too, so "has my requeue already run / did another replica
        # already take this stream" is answered atomically (a plain
        # membership check would race a sibling replica's pop)
        self.owner = None
        # padded block-table row, cached at admission: the table is
        # immutable for the stream's in-flight life (worst-case blocks
        # reserved up front), so the decode hot path must not rebuild it
        # per token
        self.table_row = None
        # chunked-prefill progress: context positions [fill_pos, fill_len)
        # still need their KV written (fill_pos starts past any shared
        # prefix); the stream joins decode once fill_pos == fill_len
        self.fill_pos = 0
        self.fill_len = 0
        self.fill_chunks = 0

    @property
    def context(self):
        return self.request.prompt + self.handle.tokens

    @property
    def filling(self):
        return self.fill_pos < self.fill_len

    def expired(self, now):
        return self.deadline is not None and now > self.deadline

    def finished(self):
        """Emitted everything it ever will (budget spent, or EOS) — but
        not yet retired. Normally _finish_check retires in the same step;
        a requeued stream can arrive in this state when a fault landed in
        between."""
        tokens = self.handle.tokens
        if len(tokens) >= self.request.max_new_tokens:
            return True
        return (self.request.eos_id is not None and tokens
                and tokens[-1] == self.request.eos_id)


class RequestQueue:
    """Bounded admission queue, shareable across replicas. `push` sheds at
    capacity; `requeue` (recovery re-entry) is cap-exempt and goes to the
    FRONT — a stream must never be shed by its own replica's death."""

    def __init__(self, cap=None):
        from ..analysis import lockguard
        self.cap = int(cap or default_queue_cap())
        self._items = deque()
        self._cond = lockguard.condition("serve.queue")

    def push(self, stream):
        with self._cond:
            if len(self._items) >= self.cap:
                raise Overloaded(
                    "serve queue full (%d waiting, cap %d)"
                    % (len(self._items), self.cap),
                    reason="queue_full", queue_depth=len(self._items),
                    retry_after_s=0.1)
            stream.owner = self
            self._items.append(stream)
            depth = len(self._items)
            self._cond.notify_all()
        _telem.set_gauge("serve.queue_depth", depth)

    def requeue(self, stream):
        with self._cond:
            stream.owner = self
            self._items.appendleft(stream)
            depth = len(self._items)
            self._cond.notify_all()
        _telem.set_gauge("serve.queue_depth", depth)

    def pop(self, owner=None):
        """Pop the head stream, atomically transferring ownership to
        `owner` (the popping replica) under the queue lock."""
        with self._cond:
            if not self._items:
                return None
            stream = self._items.popleft()
            stream.owner = owner
            depth = len(self._items)
        _telem.set_gauge("serve.queue_depth", depth)
        return stream

    def owned_by(self, stream, who):
        """Atomic ownership check — recovery's 'is this mid-admission
        stream still MINE to drain, or did my requeue already hand it
        off (possibly straight into a sibling replica's pop)?'"""
        with self._cond:
            return stream.owner is who

    def wait_nonempty(self, timeout=None):
        with self._cond:
            if self._items:
                return True
            self._cond.wait(timeout)
            return bool(self._items)

    def __len__(self):
        with self._cond:
            return len(self._items)


class InferenceServer:
    """One fault-tolerant continuous-batching serving replica.

    Usage::

        server = mx.serve.InferenceServer(params, cfg)
        server.warmup()                       # AOT-compile all programs
        h = server.submit(mx.serve.Request([1, 2, 3], max_new_tokens=8))
        server.run()                          # drive until idle
        print(h.result())

    Speculative decoding rides a draft model::

        server = mx.serve.InferenceServer(
            params, cfg, draft_params=dparams, draft_cfg=dcfg, spec_k=4)
    """

    def __init__(self, params, cfg, *, max_batch=None, kv_blocks=None,
                 block_size=None, max_context=None, chunk_size=None,
                 prefill_rows=None, prefill_budget=None,
                 prefix_sharing=None, draft_params=None, draft_cfg=None,
                 spec_k=None, queue=None, queue_cap=None,
                 step_deadline_s=None, max_restarts=3, name="replica0"):
        self.name = name
        self.cfg = cfg
        self.pool = KVBlockPool(cfg, num_blocks=kv_blocks,
                                block_size=block_size,
                                prefix_sharing=prefix_sharing)
        if max_context is None:
            max_context = min(cfg.max_seq_len,
                              self.pool.num_blocks * self.pool.block_size)
        self.max_batch = int(max_batch or default_max_batch())
        # the draft pool mirrors the target pool's geometry and BLOCK IDS
        # (one table indexes both) — accounting lives only on the target
        # pool, the draft pool is pure storage
        self.draft_pool = None
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("serve: draft_params needs draft_cfg")
            self.draft_pool = KVBlockPool(
                draft_cfg, num_blocks=self.pool.num_blocks,
                block_size=self.pool.block_size, prefix_sharing=False,
                scope="kv_draft")
        self.programs = ServePrograms(
            params, cfg, self.pool, self.max_batch, max_context,
            chunk_size=chunk_size, prefill_rows=prefill_rows,
            draft_params=draft_params, draft_cfg=draft_cfg,
            draft_pool=self.draft_pool, spec_k=spec_k)
        self.prefill_budget = (int(prefill_budget) if prefill_budget
                               else default_prefill_budget(
                                   self.programs.prefill_rows,
                                   self.programs.chunk_size))
        self.queue = queue if queue is not None else RequestQueue(queue_cap)
        self.step_deadline_s = (step_deadline_s if step_deadline_s
                                is not None else _step_deadline_s())
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.dead = False
        self._slots = [None] * self.max_batch
        # the stream currently mid-admission (popped from the queue but
        # not yet in a slot): a fault landing inside _admit — including
        # the watchdog's ASYNC StallError, which can fire between any two
        # bytecodes of the KV reservation — must find it here, or recovery
        # would drain only _slots and silently lose the stream
        self._admitting = None
        # request ids retired during the CURRENT step — reset at step
        # start, embedded (with the active set) in the step's flight
        # record so a stall post-mortem names the in-flight requests
        self._step_completed = []
        self._default_retries = RetryPolicy().max_attempts

    # ------------------------------------------------------------ admission
    def _worst_blocks(self, request):
        """Blocks reserved at admission: the FULL possible context. Greedy
        reservation keeps the invariant that an admitted stream can always
        finish — mid-stream KV exhaustion cannot exist. The final emitted
        token's KV is never written (the stream retires before feeding
        it), so the worst case is prompt + max_new_tokens - 1 positions."""
        return self.pool.blocks_for(len(request.prompt)
                                    + request.max_new_tokens - 1)

    def _note_shed(self, reason, detail=""):
        _telem.inc("serve.shed")
        _telem.inc("serve.shed.%s" % reason)
        _flight.note_event("serve_shed",
                           "%s %s" % (reason, detail) if detail else reason)

    def _shed(self, exc, reason):
        self._note_shed(reason)
        raise exc

    def submit(self, request):
        """Admit a request into the queue; returns a `StreamHandle`.
        Raises `Overloaded` (structured, never an OOM later) when the
        queue is full or the request can never fit the KV pool."""
        _faults.check("serve.admit", context="request=%s"
                      % request.request_id)
        _telem.inc("serve.requests")
        # the request's trace starts at enqueue: even a shed request
        # leaves a timeline in the last-N ring (/requests)
        trace = _reqtrace.start(request.request_id)
        # the longest context this request can ever re-prefill (a resumed
        # stream prefills prompt + all-but-one emitted budget)
        max_prefill = len(request.prompt) + request.max_new_tokens - 1
        if (self._worst_blocks(request) > self.pool.num_blocks
                or max_prefill > self.programs.max_context):
            trace.finish("shed.too_large", tokens=0)
            self._shed(Overloaded(
                "request %s can never fit: prompt %d + budget %d tokens "
                "vs pool of %d blocks x %d (max context %d)"
                % (request.request_id, len(request.prompt),
                   request.max_new_tokens, self.pool.num_blocks,
                   self.pool.block_size, self.programs.max_context),
                reason="too_large",
                kv_needed_blocks=self._worst_blocks(request),
                kv_free_blocks=self.pool.free_blocks), "too_large")
        handle = StreamHandle(request)
        handle.trace = trace
        retries = (request.retries if request.retries is not None
                   else self._default_retries)
        stream = _Stream(handle, retries_left=retries)
        try:
            self.queue.push(stream)
        except Overloaded:
            trace.finish("shed.queue_full", tokens=0)
            self._note_shed("queue_full")
            raise
        return handle

    # ------------------------------------------------------------- stepping
    def warmup(self):
        self.programs.warmup()
        return self

    def _free_slot(self):
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _finish_trace(self, handle, outcome):
        """Snapshot the request's trace into the last-N ring (idempotent:
        an earlier, more specific finish wins)."""
        ttft = (round(handle.ttft_ms, 3) if handle.ttft_ms is not None
                else None)
        return handle.trace.finish(outcome, tokens=len(handle.tokens),
                                   ttft_ms=ttft,
                                   requeues=handle.requeues)

    def _retire(self, slot, stream, error=None):
        # terminal event FIRST: if an async fault lands mid-retire, the
        # stream is done-marked while still findable in its slot, and
        # _drain_stream's done() branch finishes the cleanup — the other
        # order would strand a finished stream in neither place
        if error is not None:
            self._finish_trace(stream.handle,
                               "deadline" if isinstance(
                                   error, DeadlineExceeded) else "failed")
            stream.handle._fail(error)
        else:
            self._finish_trace(stream.handle, "completed")
            _telem.inc("serve.completed")
            stream.handle._complete()
        self._step_completed.append(stream.handle.id)
        self.pool.free(stream.kv_id)
        self._slots[slot] = None

    def _finish_check(self, slot, stream, token, now):
        handle = stream.handle
        request = stream.request
        if stream.expired(now):
            self._note_shed("deadline", stream.handle.id)
            payload = self._finish_trace(handle, "deadline")
            self._retire(slot, stream, DeadlineExceeded(
                "request %s missed its %.3gs deadline after %d token(s)"
                % (request.request_id, request.deadline_s,
                   len(handle.tokens)), tokens=handle.tokens,
                request_trace=payload))
            return True
        if (len(handle.tokens) >= request.max_new_tokens
                or (request.eos_id is not None
                    and token == request.eos_id)):
            self._retire(slot, stream)
            return True
        return False

    def _admit(self):
        """Fill free batch slots from the queue: pop → reserve KV (sharing
        any cached prompt prefix, copy-on-write at the divergence block)
        → join the batch in the *filling* state; the step's chunked
        prefill phase writes the context. A transiently unfit head
        request goes back to the front and admission stops (backpressure,
        streams keep decoding)."""
        admitted = 0
        while True:
            slot = self._free_slot()
            if slot is None:
                break
            # chained assignment marks the stream in-flight in the same
            # statement that pops it (a fault can land at any bytecode
            # boundary; the remaining pop->mark window is ~one store);
            # the pop also transfers queue-lock-governed ownership to us
            self._admitting = stream = self.queue.pop(self)
            if stream is None:
                break
            # the wait just ended: close it on the request's timeline
            # ("queue", or "recovery.queue" after a drain) and record
            # which replica now holds the stream — the cross-replica hop
            # list of a recovered request
            trace = stream.handle.trace
            trace.mark("queue", replica=self.name).note_replica(self.name)
            if stream.finished():
                # a fault landed between the stream's last token and its
                # _finish_check: it came back complete — retire it here
                # instead of re-prefilling one token too many
                self._finish_trace(stream.handle, "completed")
                self._step_completed.append(stream.handle.id)
                _telem.inc("serve.completed")
                stream.handle._complete()
                self._admitting = None
                continue
            now = time.monotonic()
            if stream.expired(now):
                self._note_shed("deadline", stream.handle.id)
                payload = self._finish_trace(stream.handle, "deadline")
                self._step_completed.append(stream.handle.id)
                stream.handle._fail(DeadlineExceeded(
                    "request %s missed its %.3gs deadline in the queue"
                    % (stream.handle.id, stream.request.deadline_s),
                    tokens=stream.handle.tokens, request_trace=payload))
                self._admitting = None
                continue
            context = stream.context
            try:
                _, fill_start, cow = self.pool.admit(
                    stream.kv_id,
                    len(stream.request.prompt)
                    + stream.request.max_new_tokens - 1, context=context)
            except Overloaded:
                # transient: the pool drains as running streams finish
                self.queue.requeue(stream)
                self._admitting = None
                break
            # the table is immutable for the stream's in-flight life
            # (worst case reserved above): build the padded row once,
            # the prefill/decode hot paths must not rebuild it per token
            stream.table_row = self.pool.table(
                stream.kv_id, self.programs.blocks_per_stream)
            stream.fill_pos = fill_start
            stream.fill_len = len(context)
            stream.fill_chunks = 0
            if cow is not None:
                # copy-on-write at the divergence block: the partially
                # matched source block's KV is bit-identical below
                # fill_start, so copy it instead of recomputing
                self.programs.copy_block(*cow)
                if self.draft_pool is not None:
                    self.programs.draft_copy_block(*cow)
            self._slots[slot] = stream
            self._admitting = None
            _telem.inc("serve.admitted")
            admitted += 1
        return admitted

    # -------------------------------------------------------------- prefill
    def _plan_chunks(self):
        """Assign this step's prefill windows: up to `prefill_rows` rows of
        up to `chunk_size` tokens, total capped by the token budget.
        Round-robin — every filling stream gets a row before any stream
        gets its second — so a burst of arrivals shares the window and a
        long prompt cannot monopolize it."""
        filling = [s for s in self._slots if s is not None and s.filling]
        if not filling:
            return []
        plan = []                   # (stream, start, n)
        progress = {s.kv_id: s.fill_pos for s in filling}
        budget = self.prefill_budget
        rows = self.programs.prefill_rows
        while len(plan) < rows and budget > 0:
            advanced = False
            for s in filling:
                if len(plan) >= rows or budget <= 0:
                    break
                rem = s.fill_len - progress[s.kv_id]
                if rem <= 0:
                    continue
                n = min(rem, self.programs.chunk_size, budget)
                plan.append((s, progress[s.kv_id], n))
                progress[s.kv_id] += n
                budget -= n
                advanced = True
            if not advanced:
                break
        return plan

    def _prefill(self):
        """One chunked-prefill window: scatter the planned chunks' KV
        (target model, and the draft mirror when spec is on) and emit the
        first token of every stream whose fill completes."""
        plan = self._plan_chunks()
        if not plan:
            return 0
        P, C = self.programs.prefill_rows, self.programs.chunk_size
        nb = self.programs.blocks_per_stream
        tokens = np.zeros((P, C), np.int32)
        positions = np.full((P, C), -1, np.int32)
        tables = np.full((P, nb), self.pool.num_blocks, np.int32)
        seeds = np.zeros(P, np.uint32)
        sample_pos = np.zeros(P, np.int32)
        temps = np.zeros(P, np.float32)
        top_k = np.zeros(P, np.int32)
        top_p = np.ones(P, np.float32)
        final_row = {}              # kv_id -> (row, stream)
        for r, (s, start, n) in enumerate(plan):
            ctx = s.context
            tokens[r, :n] = ctx[start:start + n]
            positions[r, :n] = np.arange(start, start + n)
            tables[r] = s.table_row
            req = s.request
            seeds[r] = req.seed
            sample_pos[r] = s.fill_len
            temps[r] = req.temperature
            top_k[r] = req.top_k
            top_p[r] = req.top_p
            s.fill_chunks += 1
            if start + n >= s.fill_len:
                final_row[s.kv_id] = (r, s)
        t0 = time.perf_counter()
        out = self.programs.chunk_prefill(tokens, positions, tables, seeds,
                                          sample_pos, temps, top_k, top_p)
        if self.draft_pool is not None:
            self.programs.draft_prefill(tokens, positions, tables)
        _telem.inc("serve.prefill_chunks", len(plan))
        _telem.inc("serve.prefill_chunk_tokens",
                   int(sum(n for _, _, n in plan)))
        _telem.observe("serve.prefill_ms", (time.perf_counter() - t0) * 1e3)
        for s, start, n in plan:
            s.fill_pos = max(s.fill_pos, start + n)
        now = time.monotonic()
        for r, s in final_row.values():
            # the fill is complete: the row's sampled token is the
            # stream's first output token, and its full prompt prefix is
            # now cacheable for the next user of the same system prompt
            token = int(out[r])
            _telem.inc("serve.prefills")
            self.pool.register_prefix(s.kv_id, s.request.prompt)
            s.handle.tokens.append(token)
            s.last_token_t = now
            s.handle.trace.mark("prefill", tokens=s.fill_len,
                                chunks=s.fill_chunks)
            _telem.inc("serve.tokens")
            if s.handle.ttft_ms is None:
                # time-to-first-token counts the queue wait, not just the
                # prefill — that is the latency the client experienced
                s.handle.ttft_ms = (time.perf_counter()
                                    - s.t_submit) * 1e3
                _telem.observe("serve.ttft_ms", s.handle.ttft_ms)
            slot = self._slots.index(s)
            self._finish_check(slot, s, token, now)
        return len(plan)

    # --------------------------------------------------------------- decode
    def _emit(self, slot, stream, token, now, dt_share):
        """Append one decoded token to the stream and run the retirement
        checks. Returns True when the stream retired."""
        stream.handle.tokens.append(token)
        _telem.inc("serve.tokens")
        if stream.last_token_t is not None:
            stream.handle.tpot_ms.append(dt_share)
            _telem.observe("serve.tpot_ms", dt_share)
        stream.last_token_t = now
        # one decode span per emitted token: the inter-token interval,
        # so slot residency tiles the request's timeline completely
        stream.handle.trace.mark("decode", token=len(stream.handle.tokens))
        return self._finish_check(slot, stream, token, now)

    def _spec_eligible(self, stream):
        return (self.programs.spec and stream.request.greedy)

    def _decode_plain(self, active):
        """One decode step over `active` [(slot, stream)] (fixed program
        shape: the other slots ride along masked)."""
        B = self.max_batch
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        tables = np.full((B, self.programs.blocks_per_stream),
                         self.pool.num_blocks, np.int32)
        seeds = np.zeros(B, np.uint32)
        temps = np.zeros(B, np.float32)
        top_k = np.zeros(B, np.int32)
        top_p = np.ones(B, np.float32)
        for i, s in active:
            req = s.request
            tokens[i] = s.handle.tokens[-1]
            positions[i] = len(s.context) - 1
            tables[i] = s.table_row
            seeds[i] = req.seed
            temps[i] = req.temperature
            top_k[i] = req.top_k
            top_p[i] = req.top_p
        out = self.programs.decode(tokens, positions, tables, seeds,
                                   temps, top_k, top_p)
        now = time.monotonic()
        for i, s in active:
            dt = ((now - s.last_token_t) * 1e3
                  if s.last_token_t is not None else 0.0)
            self._emit(i, s, int(out[i]), now, dt)
        return len(active)

    def _decode_spec(self, active):
        """Draft-k / verify acceptance round over `active` greedy streams:
        ONE draft program proposes spec_k tokens per stream, ONE verify
        pass computes the target's greedy token at every drafted
        position, and the matching prefix (+ the target's own next token)
        is emitted — 1..spec_k+1 tokens per stream per round, byte-
        identical to plain greedy decode by construction."""
        B, k = self.max_batch, self.programs.spec_k
        nb = self.programs.blocks_per_stream
        tokens = np.zeros(B, np.int32)
        positions = np.full(B, -1, np.int32)
        tables = np.full((B, nb), self.pool.num_blocks, np.int32)
        for i, s in active:
            tokens[i] = s.handle.tokens[-1]
            positions[i] = len(s.context) - 1
            tables[i] = s.table_row
        drafted = self.programs.draft_k(tokens, positions, tables)
        # verify window: [last token, d1..dk] at positions p..p+k — the
        # target's greedy answer at column j is the token FOLLOWING the
        # fed token, so column j+1's feed is valid iff it matched.
        # Columns past the stream's REMAINING BUDGET are masked to -1:
        # their positions would overrun the reserved block range, and an
        # out-of-range scatter clamps into the stream's own last block —
        # overwriting valid KV rows (the draft loop can still overrun its
        # mirror pool, which only costs accept rate, never output)
        vt = np.zeros((B, k + 1), np.int32)
        vp = np.full((B, k + 1), -1, np.int32)
        vt[:, 0] = tokens
        vt[:, 1:] = drafted
        remaining = {}
        for i, s in active:
            rem = s.request.max_new_tokens - len(s.handle.tokens)
            remaining[i] = rem
            cols = min(k + 1, max(rem, 1))
            vp[i, :cols] = np.arange(positions[i], positions[i] + cols)
        ver = self.programs.verify(vt, vp, tables)
        _telem.inc("serve.spec.rounds")
        now = time.monotonic()
        for i, s in active:
            cap = min(k, max(remaining[i] - 1, 0))
            accept = 0
            while accept < cap and drafted[i, accept] == ver[i, accept]:
                accept += 1
            emit = [int(t) for t in ver[i, :accept + 1]]
            # drafted = drafts that REACHED verification: accept_rate is
            # a draft-quality metric, and a budget-capped final round
            # must not dilute it (the surplus counts as discarded)
            _telem.inc("serve.spec.drafted", cap)
            _telem.inc("serve.spec.accepted", accept)
            _telem.inc("serve.spec.rejected", cap - accept)
            if k > cap:
                _telem.inc("serve.spec.discarded", k - cap)
            dt = ((now - s.last_token_t) * 1e3 / len(emit)
                  if s.last_token_t is not None else 0.0)
            for token in emit:
                if self._emit(i, s, token, now, dt):
                    break
        return len(active)

    def _decode(self):
        """One decode phase over every slot whose fill is complete:
        spec-eligible (greedy) streams ride the draft/verify loop, the
        rest (sampled, or no draft model) the plain decode program."""
        active = [(i, s) for i, s in enumerate(self._slots)
                  if s is not None and not s.filling]
        if not active:
            return 0
        spec = [(i, s) for i, s in active if self._spec_eligible(s)]
        plain = [(i, s) for i, s in active if not self._spec_eligible(s)]
        decoded = 0
        if spec:
            decoded += self._decode_spec(spec)
        if plain:
            decoded += self._decode_plain(plain)
        _telem.inc("serve.decode_steps")
        return decoded

    def step(self):
        """One scheduler iteration: (maybe) admit, (maybe) prefill a
        chunk window, (maybe) decode. Returns True while there is
        in-flight or queued work. Raises the injected/real
        `RetriableError`s the recovery path (`run`) absorbs."""
        if not self.programs._warm:
            self.warmup()
        t0 = time.perf_counter()
        ts = _telem.span_clock()
        self._step_completed = []
        with _watchdog.guard("serve.step", deadline_s=self.step_deadline_s):
            _faults.check("serve.step", context="replica=%s" % self.name)
            admitted = self._admit()
            prefilled = self._prefill()
            decoded = self._decode()
        occupancy = sum(1 for s in self._slots if s is not None)
        _telem.set_gauge("serve.batch_occupancy", occupancy)
        # admission-only steps (e.g. a max_new_tokens=1 request retired at
        # prefill) must still land in the step plane, or their completed
        # ids never reach a flight post-mortem
        if decoded or admitted or prefilled or self._step_completed:
            dur = time.perf_counter() - t0
            _telem.observe("serve.step_ms", dur * 1e3)
            # the serving cadence joins the step-span plane: attribution
            # decomposes these windows exactly like training steps
            _telem.record_span("serve.step", "step", ts, dur)
            info = {"active_requests":
                    [s.handle.id for s in self._slots
                     if s is not None][:16]}
            if self._step_completed:
                info["completed_requests"] = self._step_completed[:16]
            _telem.step_event("serve.step", dur * 1e3, info=info)
        return occupancy > 0 or len(self.queue) > 0

    # ------------------------------------------------------------- recovery
    def _drain_stream(self, stream, exc):
        """Free one in-flight stream's blocks and send it back to the
        queue (front, budget decremented) — or fail it when the budget is
        spent. Returns 1 when the stream was requeued."""
        stream.table_row = None     # blocks are going back to the pool
        stream.fill_pos = stream.fill_len = 0
        if stream.handle.done():
            # retirement's terminal event already fired when the fault
            # landed; only the pool/slot cleanup remained
            self.pool.free(stream.kv_id)
            return 0
        self.pool.free(stream.kv_id)
        stream.retries_left -= 1
        if stream.retries_left < 0:
            _telem.inc("serve.failed")
            stream.handle.trace.mark("recovery.drain",
                                     error=type(exc).__name__)
            self._finish_trace(stream.handle, "failed")
            stream.handle._fail(RetryExhausted(
                "stream %s: replica-fault retry budget spent; last "
                "error: %s: %s" % (stream.handle.id,
                                   type(exc).__name__, exc),
                site="serve.step", last_error=exc))
            return 0
        stream.handle.requeues += 1
        # timeline: activity → fault is "recovery.drain"; the wait until
        # re-admission (here or on a surviving replica) will close as
        # "recovery.queue" — the recovery cost is fully attributed
        stream.handle.trace.note_drain(exc)
        self.queue.requeue(stream)
        _telem.inc("serve.requeued_streams")
        return 1

    def _recover(self, exc):
        """Drain after a replica fault: every in-flight stream — the batch
        slots AND a stream caught mid-admission — frees its blocks and
        re-enters the shared queue (front) to resume, here or on a
        surviving replica, by re-prefill. Budget-exhausted streams fail
        with `RetryExhausted` instead of looping forever."""
        drained = 0
        requeued_ids, lost_ids = [], []

        def drain(stream):
            nonlocal drained
            n = self._drain_stream(stream, exc)
            drained += n
            # n=0 means the stream did NOT resume (retry budget spent, or
            # already done) — the post-mortem must not claim it was
            (requeued_ids if n else lost_ids).append(stream.handle.id)

        admitting, self._admitting = self._admitting, None
        if admitting is not None and not admitting.handle.done() \
                and self.queue.owned_by(admitting, self):
            # drain the mid-admission stream ONLY if it is still OURS:
            # if the fault landed in the one-bytecode window after our
            # requeue ran (ownership already handed to the queue — or
            # beyond, to a sibling replica's pop), a second requeue would
            # admit one stream into two slots. The owner field is written
            # and read under the queue lock, so this cannot race a
            # sibling's pop the way a membership check would.
            drain(admitting)
        for i, stream in enumerate(self._slots):
            if stream is None:
                continue
            self._slots[i] = None
            if stream is admitting:
                # the fault landed between slot assignment and the
                # _admitting clear: the stream is in BOTH places — drain
                # it once, or two admissions would share one handle and
                # one block table (duplicated, corrupted output)
                continue
            drain(stream)
        # a fault between a donating program call and pool.update leaves
        # deleted pool buffers; every stream re-prefills anyway, so just
        # re-materialize the storage — but a re-materialized pool is
        # ZEROS, so every cached prefix must go with it (a later match
        # would hand out garbage KV)
        reset = self.pool.ensure_storage()
        if self.draft_pool is not None:
            # draft wreckage alone only costs accept-rate, but a cleared
            # target index must not leave draft rows pretending to match
            reset = self.draft_pool.ensure_storage() or reset
        if reset:
            self.pool.clear_prefix_cache()
        # ... and one landing inside an alloc/free can tear the free-list
        # or a shared block's refcount (blocks in neither a table nor the
        # list, or counted under the wrong number of owners): rebuild
        # both as the exact complement of the surviving tables + index
        self.pool.reconcile()
        _telem.inc("serve.recoveries")
        # the drain post-mortem names the requests it touched, not just a
        # count — the flight ring's serve_recover event IS the answer to
        # "whose streams did that dead replica hold?"
        msg = ("%s: %s (requeued %d: %s)"
               % (self.name, type(exc).__name__, drained,
                  ",".join(requeued_ids[:8]) if requeued_ids else "-"))
        if lost_ids:
            msg += " (not requeued: %s)" % ",".join(lost_ids[:8])
        _flight.note_event("serve_recover", msg)
        return drained

    def run(self, max_steps=None, stop=None):
        """Drive the scheduler: until idle (stop=None — the batch-drain
        mode tests and benches use), or until `stop` (an Event) is set —
        the long-lived replica-thread mode, parking on the queue when
        idle. Retriable faults drain-and-continue up to `max_restarts`;
        past the budget the replica re-raises (marked `dead`) with its
        streams already requeued for the survivors."""
        steps = 0
        t0 = time.perf_counter()
        tokens0 = _telem.registry.counter("serve.tokens").value
        try:
            while stop is None or not stop.is_set():
                try:
                    busy = self.step()
                except RetriableError as exc:
                    self._recover(exc)
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        self.dead = True
                        _telem.inc("serve.replica_deaths")
                        raise
                    continue
                except Exception as exc:
                    # a NON-retriable escape (device loss surfacing as a
                    # runtime error, a programming bug) still must not
                    # strand in-flight streams: drain them to the queue
                    # for the survivors, then die
                    self._recover(exc)
                    self.dead = True
                    _telem.inc("serve.replica_deaths")
                    raise
                steps += 1
                if max_steps is not None and steps >= max_steps:
                    break
                if not busy:
                    if stop is None:
                        break
                    self.queue.wait_nonempty(timeout=0.05)
        finally:
            dt = time.perf_counter() - t0
            if dt > 0:
                tokens = (_telem.registry.counter("serve.tokens").value
                          - tokens0)
                _telem.set_gauge("serve.tokens_per_s", round(tokens / dt, 2))
        return steps
