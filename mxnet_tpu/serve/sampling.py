"""In-program token sampling: temperature / top-k / top-p, replayable.

Serving sampled streams has one hard requirement the training RNG plumbing
cannot meet: **replayability per position**. A drained or killed stream
resumes by re-prefilling its prompt + already-emitted tokens and must then
draw the SAME future tokens it would have drawn uninterrupted — so the
draw for the token occupying position p may depend only on (stream seed,
p, logits), never on a global key table's consumption order. Every draw
therefore derives its key as ``fold_in(PRNGKey(seed), position)``: pure,
stateless, identical on any replica at any time.

Sampling happens INSIDE the decode/chunk-prefill programs (one int32 per
stream crosses the device boundary, not a vocab row), with per-slot
parameter vectors so one fixed-shape executable serves every mixture of
greedy and sampled streams:

* ``temperature <= 0`` — greedy: exactly ``argmax`` (bit-identical to the
  sampling-free path; the sampled branch's value is discarded by a
  ``where``);
* ``top_k > 0`` — keep only the k highest logits (value threshold: ties
  at the boundary all stay eligible);
* ``top_p < 1`` — nucleus: keep the smallest probability-ordered set
  whose cumulative mass reaches top_p (the top-1 token is always kept).

Filter order is temperature → top-k → top-p (the HF convention).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["sample_tokens"]

_NEG_INF = -1e30


def _sample(logits, seeds, positions, temperature, top_k, top_p, greedy):
    """The full filter + draw pipeline (the lax.cond sampled branch)."""
    V = logits.shape[1]
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    order = jnp.sort(scaled, axis=-1)[:, ::-1]          # descending
    # top-k: everything below the kth-largest scaled logit drops
    k = jnp.clip(jnp.where(top_k > 0, top_k, V), 1, V)
    kth = jnp.take_along_axis(order, k[:, None] - 1, axis=1)
    masked = jnp.where(scaled >= kth, scaled, _NEG_INF)
    # top-p over what survived top-k: walk the sorted probabilities and
    # keep rows whose PRECEDING cumulative mass is still under top_p —
    # the top-1 token's preceding mass is 0, so it always survives
    order_m = jnp.where(order >= kth, order, _NEG_INF)
    probs = jax.nn.softmax(order_m, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    keep_p = before < jnp.minimum(top_p, 1.0)[:, None]
    # value threshold of the last kept sorted entry
    thresh = jnp.min(jnp.where(keep_p, order_m, jnp.inf), axis=-1)
    masked = jnp.where(scaled >= thresh[:, None], masked, _NEG_INF)

    def draw(seed, pos, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(draw)(seeds.astype(jnp.uint32), positions,
                             masked).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def sample_tokens(logits, seeds, positions, temperature, top_k, top_p):
    """Draw one token per row. All inputs row-aligned:

    logits (N, V) fp32; seeds (N,) uint32 — the stream's sampling seed;
    positions (N,) int32 — the position the NEW token will occupy (the
    replay key); temperature (N,) fp32 (<= 0 selects greedy argmax);
    top_k (N,) int32 (0 disables); top_p (N,) fp32 (>= 1 disables).
    Returns (N,) int32.

    The sampling pipeline (a vocab-wide sort + softmax + cumsum) rides a
    `lax.cond` on "any row sampled?": an all-greedy batch — the common
    decode-hot-path case — pays only the argmax at runtime, in the SAME
    fixed-shape executable (no second program, no retrace).
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return lax.cond(
        jnp.any(temperature > 0.0),
        lambda args: _sample(*args),
        lambda args: greedy,
        (logits, seeds, positions, temperature, top_k, top_p, greedy))
